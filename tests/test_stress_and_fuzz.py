"""Robustness beyond the reference's 3-node scenarios: a larger live
topology on the sockets backend, and seeded fuzz over both stream
decoders (the reference's framing scan has no tests at all for malformed
input [ref: tests/test_nodeconnection.py:4-5])."""

import random

import pytest

from p2pnetwork_tpu import Node, wire
from tests.helpers import EventRecorder, stop_all, wait_until


class TestManyNodeTopology:
    def test_twenty_node_ring_gossip_delivers_everywhere(self):
        # 20 nodes in a directed ring; a token broadcast hop-by-hop (each
        # node forwards first sightings) must reach every node — the
        # flood protocol the reference tells users to write themselves,
        # at a size its suite never exercises.
        n_nodes = 20
        recs = [EventRecorder() for _ in range(n_nodes)]
        nodes = []

        def make_cb(i):
            def cb(event, main_node, connected_node, data):
                recs[i](event, main_node, connected_node, data)
                if event == "node_message" and data not in getattr(
                        main_node, "_seen_msgs", set()):
                    seen = getattr(main_node, "_seen_msgs", set())
                    seen.add(data)
                    main_node._seen_msgs = seen
                    main_node.send_to_nodes(data)  # forward along the ring
            return cb

        for i in range(n_nodes):
            node = Node("127.0.0.1", 0, callback=make_cb(i), id=f"n{i}")
            node.start()
            nodes.append(node)
        try:
            for i in range(n_nodes):
                assert nodes[i].connect_with_node(
                    "127.0.0.1", nodes[(i + 1) % n_nodes].port)
            assert wait_until(
                lambda: all(len(n.nodes_outbound) == 1 for n in nodes),
                timeout=15.0)
            nodes[0].send_to_nodes("token-7")
            assert wait_until(
                lambda: all("token-7" in r.messages() for r in recs[1:]),
                timeout=20.0)
        finally:
            stop_all(nodes)

    def test_fanout_hub_with_many_spokes(self):
        # One hub, 15 spokes; hub broadcast reaches all spokes, spoke
        # unicasts reach the hub — max_connections=0 (unlimited) parity.
        hub_rec = EventRecorder()
        hub = Node("127.0.0.1", 0, callback=hub_rec, id="hub")
        hub.start()
        spokes, recs = [], []
        try:
            for i in range(15):
                r = EventRecorder()
                s = Node("127.0.0.1", 0, callback=r, id=f"s{i}")
                s.start()
                assert s.connect_with_node("127.0.0.1", hub.port)
                spokes.append(s)
                recs.append(r)
            assert wait_until(lambda: len(hub.nodes_inbound) == 15,
                              timeout=15.0)
            hub.send_to_nodes({"round": 1})
            assert wait_until(
                lambda: all({"round": 1} in r.messages() for r in recs),
                timeout=15.0)
            for s in spokes:
                s.send_to_nodes(f"ack-{s.id}")
            assert wait_until(
                lambda: len(hub_rec.messages()) == 15, timeout=15.0)
        finally:
            stop_all([hub] + spokes)


class TestDecoderFuzz:
    """Seeded random streams through both decoders: no crash, bounded
    buffers, and every well-formed frame that goes in comes out."""

    @pytest.mark.parametrize("framing", ["eot", "length"])
    @pytest.mark.parametrize("seed", [0, 1, 7, 12, 42])
    def test_roundtrip_under_random_chunking(self, framing, seed):
        rng = random.Random(seed)
        payloads = []
        for _ in range(200):
            kind = rng.randrange(3)
            if kind == 0:
                payloads.append("".join(chr(rng.randrange(32, 127))
                                        for _ in range(rng.randrange(0, 300))))
            elif kind == 1:
                payloads.append({"k": rng.randrange(1000),
                                 "v": [rng.random() for _ in range(5)]})
            else:
                body = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 200)))
                if framing == "eot":
                    # EOT framing cannot carry the delimiter, and its
                    # parse chain sniffs a trailing 0x02 as the
                    # compression marker (reference parity). Length
                    # framing carries BOTH unmodified — that is its point.
                    body = body.replace(wire.EOT_CHAR, b"\xfe")
                    while body.endswith(wire.COMPR_CHAR):
                        body = body[:-1] + b"\xfe"
                    if not body:
                        body = b"\xfe"
                payloads.append(body)
        stream = b"".join(wire.encode_frame(p, framing=framing)
                          for p in payloads)
        dec = wire.make_decoder(framing)
        parse = (wire.parse_length_body if framing == "length"
                 else wire.parse_packet)
        out = []
        i = 0
        while i < len(stream):
            step = rng.randrange(1, 50)
            out.extend(parse(b) for b in dec.feed(stream[i:i + step]))
            i += step
        assert dec.pending == 0
        assert len(out) == len(payloads)
        # bytes that happen to be valid utf-8 decode to str/json — the
        # reference's parse chain loses the type; compare decoded forms.
        for got, sent in zip(out, payloads):
            if isinstance(sent, bytes):
                assert got == wire.decode_payload(sent)
            else:
                assert got == sent

    @pytest.mark.parametrize("framing", ["eot", "length"])
    @pytest.mark.parametrize("seed", [0, 1, 7, 8, 10, 12])
    def test_garbage_never_crashes_and_buffer_stays_bounded(self, framing,
                                                            seed):
        rng = random.Random(seed)
        dec = wire.make_decoder(framing, max_buffer=4096)
        overflows = 0
        for _ in range(300):
            chunk = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(1, 400)))
            parse = (wire.parse_length_body if framing == "length"
                     else wire.parse_packet)
            try:
                for packet in dec.feed(chunk):
                    parse(packet)  # must not raise either
            except wire.FrameOverflowError:
                overflows += 1  # allowed: bound enforced, stream reset
            # Header-inclusive bound: never more than max_buffer buffered.
            assert dec.pending <= 4096
        # With random bytes the 4 KiB bound must have tripped at least
        # once in 300 x ~200 B for the length decoder (huge bogus
        # headers) — proves the bound is live, not decorative.
        if framing == "length":
            assert overflows >= 1


class TestCompressionFuzz:
    """The codec surface under hostile input: truncation, corruption, bogus
    tags, and decompression bombs — none may crash, and the bomb must be
    CONTAINED (wire.decompress max_output; the reference inherits this
    amplification unbounded [ref: nodeconnection.py:84-105])."""

    @pytest.mark.parametrize("alg", ["zlib", "bzip2", "lzma"])
    @pytest.mark.parametrize("seed", [0, 3])
    def test_roundtrip_random_binary(self, alg, seed):
        rng = random.Random(seed)
        raw = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 5000)))
        packet = wire.compress(raw, alg) + wire.COMPR_CHAR
        assert wire.parse_packet(packet) == wire.decode_payload(raw)

    @pytest.mark.parametrize("alg", ["zlib", "bzip2", "lzma"])
    @pytest.mark.parametrize("seed", [1, 9])
    def test_truncated_blob_never_raises(self, alg, seed):
        rng = random.Random(seed)
        raw = bytes(rng.randrange(256) for _ in range(2000))
        blob = wire.compress(raw, alg)
        for _ in range(40):
            cut = blob[: rng.randrange(0, len(blob))]
            out = wire.decompress(cut)  # must not raise, must return bytes
            assert isinstance(out, bytes)

    @pytest.mark.parametrize("alg", ["zlib", "bzip2", "lzma"])
    def test_corrupt_middle_byte_never_raises(self, alg):
        rng = random.Random(5)
        raw = bytes(rng.randrange(256) for _ in range(2000))
        blob = bytearray(wire.compress(raw, alg))
        for _ in range(40):
            i = rng.randrange(len(blob))
            mutated = bytes(blob[:i]) + bytes([rng.randrange(256)]) + bytes(
                blob[i + 1:])
            out = wire.decompress(mutated)
            assert isinstance(out, bytes)

    def test_unknown_tag_returns_decoded_as_is(self):
        import base64

        data = b"payload-with-bogus-tag" + b"zstd"
        assert wire.decompress(base64.b64encode(data)) == data

    def test_bomb_raises_observable_error(self):
        # ~100 KB of wire bytes expanding to 256 MB: with the bound the
        # caller gets DecompressionBombError — observable containment,
        # never the expansion and never compressed bytes masquerading as
        # the message.
        import base64
        import zlib as _z

        bomb_raw_len = 256 * 1024 * 1024
        blob = base64.b64encode(
            _z.compress(b"\x00" * bomb_raw_len, 9) + b"zlib")
        assert len(blob) < 1024 * 1024, "bomb not compact enough to matter"
        with pytest.raises(wire.DecompressionBombError):
            wire.decompress(blob, max_output=1024 * 1024)
        # And without a bound the historical behavior stands.
        full = wire.decompress(blob)
        assert len(full) == bomb_raw_len

    @pytest.mark.parametrize("alg,stitched", [
        ("bzip2", True),  # bz2/lzma concatenate streams (stdlib parity)
        ("lzma", True),
        ("zlib", False),  # zlib returns the first stream, ignores the rest
    ])
    def test_bounded_multistream_parity(self, alg, stitched):
        # Bounded decompression must not silently truncate concatenated
        # streams: parity with the unbounded stdlib semantics per codec.
        import base64
        import bz2 as _b
        import lzma as _l
        import zlib as _z

        mod = {"bzip2": _b, "lzma": _l, "zlib": _z}[alg]
        tag = {"bzip2": b"bzip2", "lzma": b"lzma", "zlib": b"zlib"}[alg]
        blob = base64.b64encode(
            mod.compress(b"AAA") + mod.compress(b"BBB") + tag)
        want = b"AAABBB" if stitched else b"AAA"
        assert wire.decompress(blob) == want
        assert wire.decompress(blob, max_output=1 << 20) == want

    @pytest.mark.parametrize("alg", ["zlib", "bzip2", "lzma"])
    def test_bounded_truncation_is_codec_failure_not_bomb(self, alg):
        # A stream cut short is corruption: the as-is contract applies
        # (no raise), exactly like the unbounded path.
        import base64

        raw = bytes(range(256)) * 64
        full = base64.b64decode(wire.compress(raw, alg))
        cut = base64.b64encode(full[: len(full) // 2])
        out = wire.decompress(cut, max_output=1 << 20)
        assert isinstance(out, bytes)

    @pytest.mark.parametrize("alg", ["zlib", "bzip2", "lzma"])
    def test_bound_does_not_reject_legitimate_payloads(self, alg):
        raw = bytes(range(256)) * 1000  # 256 KB, compressible but honest
        blob = wire.compress(raw, alg)
        assert wire.decompress(blob, max_output=len(raw)) == raw

    def test_node_recv_path_drops_bomb_frame(self):
        # End-to-end: a peer ships a zlib bomb through a real socket; the
        # receiving node must DROP the frame (counted as a receive
        # error), never allocate the expansion or deliver compressed
        # bytes as a message, and the link must survive.
        import base64
        import zlib as _z

        from tests.helpers import EventRecorder

        rec = EventRecorder()
        a = Node("127.0.0.1", 0, id="A")
        b = Node("127.0.0.1", 0, callback=rec, id="B")
        for n in (a, b):
            n.start()
        try:
            assert a.connect_with_node("127.0.0.1", b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            bomb = base64.b64encode(
                _z.compress(b"\x00" * (200 * 1024 * 1024), 9) + b"zlib")
            conn = a.nodes_outbound[0]
            a._loop.call_soon_threadsafe(
                conn._write, bomb + wire.COMPR_CHAR + wire.EOT_CHAR)
            assert wait_until(lambda: b.message_count_rerr >= 1,
                              timeout=10.0), "bomb frame not counted rerr"
            assert rec.messages() == [], "bomb frame was delivered"
            # The link survives: normal traffic still flows.
            a.send_to_nodes("still-alive")
            assert wait_until(lambda: "still-alive" in rec.messages(),
                              timeout=10.0)
        finally:
            stop_all([a, b])


class TestSocketsRaces:
    """Concurrent send/stop and hostile peers on the real sockets backend —
    the verify-skill probes, pinned as tests."""

    def test_concurrent_senders_with_midstream_stop(self):
        import threading

        got = []

        class Sink(Node):
            def node_message(self, node, data):
                got.append(data)

        a = Node("127.0.0.1", 0, id="A")
        b = Sink("127.0.0.1", 0, id="B")
        for n in (a, b):
            n.start()
        try:
            assert a.connect_with_node("127.0.0.1", b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)

            stop_evt = threading.Event()

            def blast(t):
                i = 0
                while not stop_evt.is_set():
                    try:
                        a.send_to_nodes(f"t{t}-{i}")
                    except Exception:
                        return  # node stopping underneath us is fine
                    i += 1

            threads = [threading.Thread(target=blast, args=(t,))
                       for t in range(4)]
            for th in threads:
                th.start()
            assert wait_until(lambda: len(got) > 200, timeout=15.0)
            # Stop the RECEIVER mid-stream, then the senders.
            b.stop()
            b.join(timeout=15.0)
            assert not b.is_alive(), "receiver failed to stop under load"
            stop_evt.set()
            for th in threads:
                th.join(timeout=10.0)
                assert not th.is_alive(), "sender thread wedged"
            a.stop()
            a.join(timeout=15.0)
            assert not a.is_alive(), "sender node failed to stop"
        finally:
            stop_evt.set()
            stop_all([a, b])

    def test_raw_junk_peer_does_not_wedge_accept_path(self):
        import socket as pysocket

        rng = random.Random(2)
        n = Node("127.0.0.1", 0, id="N")
        n.start()
        try:
            # No handshake, binary junk with stray EOTs, abrupt close.
            for _ in range(3):
                s = pysocket.create_connection(("127.0.0.1", n.port),
                                               timeout=5)
                s.sendall(bytes(rng.randrange(256) for _ in range(3000))
                          + wire.EOT_CHAR * 3)
                s.close()
            # A legitimate peer can still connect afterwards.
            peer = Node("127.0.0.1", 0, id="P")
            peer.start()
            try:
                assert peer.connect_with_node("127.0.0.1", n.port)
                assert wait_until(lambda: len(n.nodes_inbound) >= 1,
                                  timeout=10.0)
            finally:
                stop_all([peer])
        finally:
            stop_all([n])

    def test_invalid_compression_frames_survive_and_count(self):
        rec = []

        class Sink(Node):
            def node_message(self, node, data):
                rec.append(data)

        a = Node("127.0.0.1", 0, id="A")
        b = Sink("127.0.0.1", 0, id="B")
        for n in (a, b):
            n.start()
        try:
            assert a.connect_with_node("127.0.0.1", b.port)
            assert wait_until(lambda: len(b.nodes_inbound) == 1)
            conn = a.nodes_outbound[0]
            # Invalid base64 with the COMPR marker: parses as-is (bytes
            # back unchanged), must not kill the link.
            junk = b"!!!not-base64!!!" + wire.COMPR_CHAR + wire.EOT_CHAR
            a._loop.call_soon_threadsafe(conn._write, junk)
            a.send_to_nodes("after-junk")
            assert wait_until(lambda: "after-junk" in rec, timeout=10.0)
        finally:
            stop_all([a, b])

    def test_send_after_stop_is_a_clean_noop(self):
        # The post-stop contract: sends neither crash nor wedge — the
        # connection layer logs "node is not running" and returns (the
        # reference would raise from a dead socket instead).
        a = Node("127.0.0.1", 0, id="A")
        a.start()
        a.stop()
        a.join(timeout=10.0)
        assert not a.is_alive()
        a.send_to_nodes("too late")  # must not raise
        assert a.message_count_send == 0


def test_nonpositive_bound_contains_rather_than_disables():
    # zlib's max_length=0 means unlimited — a zero/negative bound must
    # never silently bypass containment (it raises for every codec).
    for alg in ("zlib", "bzip2", "lzma"):
        blob = wire.compress(b"x" * 10000, alg)
        for bound in (0, -5):
            with pytest.raises(wire.DecompressionBombError):
                wire.decompress(blob, max_output=bound)
