"""Plumtree: flood -> tree -> heal, each phase pinned by its invariant.

Broadcast 1 floods (duplicates ~ E - N); broadcast 2 rides the pruned
tree (exactly n_live - 1 messages, zero duplicates, full coverage — a
spanning-arborescence check against the recorded eager set); after
killing nodes, the next broadcast grafts lazy links and still covers
every live node reachable in the residual graph."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Plumtree  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _run_broadcasts(g, k, state=None, source=0):
    p = Plumtree(source=source)
    if state is None:
        state = p.init(g, jax.random.key(0))
    step = jax.jit(p.step)
    outs = []
    for _ in range(k):
        state, stats = step(g, state, jax.random.key(0))
        outs.append({k2: np.asarray(v) for k2, v in stats.items()})
    return p, state, outs


def _check_tree(g, state, source):
    """The eager set restricted to live edges into reached nodes is a
    spanning arborescence: every live node but the source has exactly
    one eager live in-edge, and parents chain back to the source."""
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    alive = np.asarray(g.node_mask)
    em = (np.asarray(g.edge_mask) & np.asarray(state.eager)
          & alive[s] & alive[r])
    live_ids = np.nonzero(alive)[0]
    indeg = np.zeros(g.n_nodes_padded, np.int32)
    np.add.at(indeg, r[em], 1)
    assert indeg[source] == 0
    others = live_ids[live_ids != source]
    assert (indeg[others] == 1).all(), "not a tree: in-degree != 1"
    parent = np.full(g.n_nodes_padded, -1, np.int64)
    parent[r[em]] = s[em]
    for v in others:
        seen, x = set(), int(v)
        while x != source:
            assert x not in seen, "cycle in eager set"
            seen.add(x)
            x = int(parent[x])
            assert x >= 0, "orphaned node"


class TestPlumtree:
    def test_flood_then_tree(self):
        g = G.watts_strogatz(500, 6, 0.1, seed=2)
        n = 500
        p, st, outs = _run_broadcasts(g, 3)
        b1, b2, b3 = outs
        # Broadcast 1: full flood — every live directed edge fires.
        assert b1["coverage"] == pytest.approx(1.0)
        assert b1["messages"] == g.n_edges
        assert b1["duplicates"] > g.n_edges - n - 50
        # Broadcast 2: the pruned tree — n-1 messages, zero duplicates.
        assert b2["coverage"] == pytest.approx(1.0)
        assert b2["messages"] == n - 1
        assert b2["duplicates"] == 0
        assert b2["eager_edges"] == n - 1
        assert b2["grafts"] == 0
        # Stable thereafter.
        assert b3["messages"] == n - 1 and b3["duplicates"] == 0
        _check_tree(g, st, 0)

    def test_heal_after_failures(self):
        g = G.watts_strogatz(400, 8, 0.2, seed=5)
        p, st, outs = _run_broadcasts(g, 2)
        # Kill 30 non-source nodes: tree links die with them.
        rng = np.random.default_rng(0)
        dead = rng.choice(np.arange(1, 400), size=30, replace=False)
        gf = failures.fail_nodes(g, dead)
        p2, st2, outs2 = _run_broadcasts(gf, 2, state=st)
        h1, h2 = outs2
        # The healing broadcast still reaches everyone (WS at degree 8
        # stays connected under 30 losses) by grafting lazy links...
        assert h1["coverage"] == pytest.approx(1.0)
        assert h1["grafts"] > 0
        # ...and the NEXT broadcast is a clean tree again.
        n_live = 400 - len(dead)
        assert h2["messages"] == n_live - 1
        assert h2["duplicates"] == 0
        _check_tree(gf, st2, 0)

    def test_disconnected_component_unreachable(self):
        # Two cliques, no bridge: the far clique can never be covered —
        # grafting must give up instead of spinning.
        half = 8
        edges = []
        for base in (0, half):
            for i in range(half):
                for j in range(i + 1, half):
                    edges.append((base + i, base + j))
        s = np.array([e[0] for e in edges] + [e[1] for e in edges],
                     np.int32)
        r = np.array([e[1] for e in edges] + [e[0] for e in edges],
                     np.int32)
        g = G.from_edges(s, r, 2 * half)
        p, st, outs = _run_broadcasts(g, 2)
        assert outs[0]["coverage"] == pytest.approx(0.5)
        assert outs[1]["messages"] == half - 1

    def test_dead_source_is_silent(self):
        g = G.watts_strogatz(64, 4, 0.1, seed=1)
        g = failures.fail_nodes(g, np.array([0]))
        p, st, outs = _run_broadcasts(g, 1, source=0)
        assert outs[0]["coverage"] == 0.0
        assert outs[0]["messages"] == 0

    def test_tree_graph_extraction(self):
        # Flood over the extracted tree graph = the tree broadcast:
        # same coverage, exactly n-1 deliveries, no duplicates possible.
        from p2pnetwork_tpu.models import Flood

        g = G.watts_strogatz(300, 6, 0.1, seed=7)
        p, st, _ = _run_broadcasts(g, 2)
        tg = p.tree_graph(g, st)
        assert tg.n_edges == 299
        stf, out = engine.run_until_coverage(
            tg, Flood(source=0), jax.random.key(0), coverage_target=1.0)
        assert float(out["coverage"]) == pytest.approx(1.0)
        assert int(out["messages"]) == 299

    def test_tree_graph_respects_dead_nodes(self):
        g = G.watts_strogatz(200, 8, 0.2, seed=9)
        p, st, _ = _run_broadcasts(g, 2)
        dead = np.array([7, 50, 100])
        gf = failures.fail_nodes(g, dead)
        p2, st2, _ = _run_broadcasts(gf, 2, state=st)
        tg = p2.tree_graph(gf, st2)
        assert not np.asarray(tg.node_mask)[dead].any()
        s = np.asarray(tg.senders)[np.asarray(tg.edge_mask)]
        r = np.asarray(tg.receivers)[np.asarray(tg.edge_mask)]
        assert not np.isin(s, dead).any() and not np.isin(r, dead).any()

    def test_tree_graph_keeps_weights(self):
        import jax.numpy as jnp

        g = G.watts_strogatz(128, 4, 0.1, seed=11).with_weights(
            lambda s, r: 1.0 + (jnp.minimum(s, r) % 7).astype(jnp.float32))
        p, st, _ = _run_broadcasts(g, 2)
        tg = p.tree_graph(g, st)
        assert tg.edge_weight is not None
        # Every extracted edge keeps its source-graph cost.
        src_w = {}
        s0 = np.asarray(g.senders); r0 = np.asarray(g.receivers)
        w0 = np.asarray(g.edge_weight); em0 = np.asarray(g.edge_mask)
        for a, b, w in zip(s0[em0], r0[em0], w0[em0]):
            src_w[(int(a), int(b))] = float(w)
        s1 = np.asarray(tg.senders); r1 = np.asarray(tg.receivers)
        w1 = np.asarray(tg.edge_weight); em1 = np.asarray(tg.edge_mask)
        for a, b, w in zip(s1[em1], r1[em1], w1[em1]):
            assert src_w[(int(a), int(b))] == float(w)

    def test_rejects_dynamic_edge_region(self):
        from p2pnetwork_tpu.sim import topology
        g = topology.with_capacity(
            G.watts_strogatz(64, 4, 0.1, seed=1), extra_edges=4)
        with pytest.raises(ValueError):
            Plumtree().init(g, jax.random.key(0))

    def test_engine_integration(self):
        # Rides the ordinary engine scan like any protocol.
        g = G.watts_strogatz(200, 4, 0.1, seed=3)
        st, stats = engine.run(g, Plumtree(source=5), jax.random.key(0), 3)
        msgs = np.asarray(stats["messages"])
        assert msgs[0] == g.n_edges and msgs[1] == 199 and msgs[2] == 199
        assert np.asarray(stats["coverage"])[-1] == pytest.approx(1.0)
