"""graftrace: the deterministic scheduler, the happens-before detector,
the concurrency seam, the scenario battery, and the CLI gate.

The load-bearing properties, each pinned directly:

- **replay determinism** — two independent explorations of one body
  under one seed produce byte-identical schedule traces AND identical
  finding sets; the CLI's --replay verifies a recorded trace the same
  way and exits 2 on divergence;
- **twin fixtures per HB edge kind** — for each of lock / start / join /
  event / queue, the deliberately-racy twin is caught at the EXACT
  ``file:line`` of its ``# RACY`` marker while the clean twin (same
  accesses, plus the one synchronization edge) passes every seed;
- **deadlock detection** — an AB/BA order inversion is found within the
  seed budget, reported as P0, and the schedule unwinds cleanly;
- **the live battery gates clean** — every builtin scenario across
  several seeds yields zero findings (races found during development
  were fixed in this PR, and the graftlint baseline entry for the crdt
  merge was replaced by a suppression citing the dynamic refutation);
- **the CLI** exits nonzero on a non-baselined race and 0 on the clean
  battery, and bumps the graftrace_* telemetry counters.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import graftrace_fixtures as fx  # noqa: E402
from p2pnetwork_tpu import concurrency, telemetry  # noqa: E402
from p2pnetwork_tpu.analysis.race import (  # noqa: E402
    DEADLOCK_RULE, RACE_RULE, Detector, Shared, explore, guarded_attrs,
    load_replay, watch, write_replay,
)
from p2pnetwork_tpu.analysis.race.__main__ import (  # noqa: E402
    main as graftrace_main, run_battery,
)
from p2pnetwork_tpu.analysis.race.scenarios import (  # noqa: E402
    SCENARIOS, builtin_names,
)

pytestmark = pytest.mark.race

FIXTURE_FILE = os.path.abspath(fx.__file__)
REPO = os.path.dirname(os.path.dirname(FIXTURE_FILE))
SEEDS = range(4)


def marker_line(marker: str = "# RACY", after: str = "") -> int:
    """1-based line of the (first) marker following the ``after`` text —
    how twin tests learn the exact line a finding must anchor at."""
    with open(FIXTURE_FILE, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    start = 0
    if after:
        start = next(i for i, ln in enumerate(lines) if after in ln)
    return next(i for i, ln in enumerate(lines[start:], start + 1)
                if marker in ln)


def races(result):
    return [f for f in result.findings if f.rule == RACE_RULE]


# ===================================================== the concurrency seam


class TestSeam:
    def test_defaults_are_stdlib(self):
        import queue
        import threading
        assert isinstance(concurrency.lock(), type(threading.Lock()))
        assert isinstance(concurrency.event(), threading.Event)
        assert isinstance(concurrency.thread(target=lambda: None),
                          threading.Thread)
        assert isinstance(concurrency.fifo_queue(), queue.Queue)
        assert concurrency.installed() is None

    def test_substituted_installs_and_restores(self):
        class P:
            def event(self):
                return "fake"
        with concurrency.substituted(P()):
            assert concurrency.event() == "fake"
        assert concurrency.installed() is None
        import threading
        assert isinstance(concurrency.event(), threading.Event)

    def test_substituted_restores_on_error(self):
        class P:
            pass
        with pytest.raises(RuntimeError):
            with concurrency.substituted(P()):
                raise RuntimeError("boom")
        assert concurrency.installed() is None

    def test_production_modules_construct_through_seam(self):
        # The refactor's point: a provider sees every primitive these
        # modules build. Count constructions while instantiating a node
        # stack.
        made = []

        class Spy:
            def lock(self):
                made.append("lock")
                import threading
                return threading.Lock()

            def event(self):
                made.append("event")
                import threading
                return threading.Event()

        with concurrency.substituted(Spy()):
            from p2pnetwork_tpu.phi import PhiAccrualNode
            node = PhiAccrualNode("127.0.0.1", 0, id="seamcheck",
                                  registry=telemetry.Registry())
            node.sock.close()
        assert "lock" in made and "event" in made


# ================================================== scheduler determinism


class TestDeterminism:
    def test_same_seed_identical_trace_and_findings(self):
        r1 = explore(fx.lock_racy, seed=3)
        r2 = explore(fx.lock_racy, seed=3)
        assert r1.trace == r2.trace
        assert [f.to_json() for f in r1.findings] == \
            [f.to_json() for f in r2.findings]
        assert r1.steps == r2.steps

    def test_different_seeds_differ_somewhere(self):
        traces = {tuple(explore(fx.lock_clean, seed=s).trace)
                  for s in range(8)}
        assert len(traces) > 1, "8 seeds produced one schedule"

    def test_unnamed_threads_replay_identically_across_runs(self):
        # Default thread names must come from per-run spawn order, not a
        # process-global counter — otherwise the second exploration of
        # the same seed in one process diverges and --replay reports a
        # false nondeterminism.
        def body():
            def w():
                pass
            t = concurrency.thread(target=w)  # deliberately unnamed
            t.start()
            t.join()
        r1 = explore(body, seed=3)
        r2 = explore(body, seed=3)
        assert r1.trace == r2.trace

    def test_trace_serialization_roundtrip(self, tmp_path):
        r = explore(fx.lock_racy, seed=5)
        path = write_replay(str(tmp_path / "t.json"), "fixture", r)
        doc = load_replay(path)
        assert doc["seed"] == 5
        assert [tuple(row) for row in doc["trace"]] == r.trace
        assert doc["findings"] == [f.to_json() for f in r.findings]

    def test_scenario_battery_replays_identically(self):
        name = "partition_heal"
        body1 = SCENARIOS[name].factory()
        body2 = SCENARIOS[name].factory()
        r1 = explore(body1, seed=9)
        r2 = explore(body2, seed=9)
        assert r1.trace == r2.trace


# ============================================== twin fixtures per HB edge


class TestTwins:
    @pytest.mark.parametrize("kind", sorted(fx.TWINS))
    def test_racy_twin_caught_at_exact_line(self, kind):
        racy, _clean = fx.TWINS[kind]
        expected = marker_line(after=f"def {racy.__name__}")
        hits = []
        for seed in SEEDS:
            hits.extend(races(explore(racy, seed=seed)))
        assert hits, f"{kind}: racy twin never caught over {len(SEEDS)} seeds"
        lines = {(f.file, f.line) for f in hits}
        rel = os.path.relpath(FIXTURE_FILE, REPO)
        assert (rel, expected) in lines, (
            f"{kind}: expected a finding at {rel}:{expected}, got {lines}")
        assert all(f.severity == "P0" for f in hits)

    @pytest.mark.parametrize("kind", sorted(fx.TWINS))
    def test_clean_twin_passes_every_seed(self, kind):
        _racy, clean = fx.TWINS[kind]
        for seed in SEEDS:
            r = explore(clean, seed=seed)
            assert not r.findings, (
                f"{kind} clean twin seed {seed}: "
                + "; ".join(f.render() for f in r.findings))
            assert not r.errors


# ========================================================= deadlock + misc


class TestDeadlock:
    @staticmethod
    def _ab_ba():
        l1, l2 = concurrency.lock(), concurrency.lock()

        def a():
            with l1:
                with l2:
                    pass

        def b():
            with l2:
                with l1:
                    pass
        fx._pair(a, b)

    def test_order_inversion_found_and_unwound(self):
        hits = [s for s in range(20)
                if any(f.rule == DEADLOCK_RULE
                       for f in explore(self._ab_ba, seed=s).findings)]
        assert hits, "AB/BA deadlock not found in 20 seeds"
        r = explore(self._ab_ba, seed=hits[0])
        assert any(f.severity == "P0" for f in r.findings)
        # The unwind is clean: DeadlockError is the report, not an error.
        assert not r.errors

    def test_timed_wait_times_out_at_quiescence(self):
        got = []

        def body():
            ev = concurrency.event()
            got.append(ev.wait(timeout=1.0))
        r = explore(body, seed=0)
        assert got == [False] and not r.findings and not r.errors

    def test_condition_notify_wakes_a_live_waiter_after_a_retired_one(self):
        # A retired ticket (a wait that already completed) must never
        # absorb a notify meant for a live waiter. Under some schedules
        # the notify legitimately precedes the second wait (False is
        # correct there), so the property is: across a handful of seeds,
        # the schedules that DO order notify after wait deliver it — a
        # retired-ticket bug makes every seed come back False.
        def run_one(seed):
            outcomes = []

            def body():
                cv = concurrency.condition()

                def first():
                    with cv:
                        outcomes.append(("first", cv.wait(timeout=1.0)))

                def second():
                    with cv:
                        outcomes.append(("second", cv.wait(timeout=1.0)))

                t1 = concurrency.thread(target=first, name="W1")
                t1.start()
                with cv:
                    cv.notify()
                t1.join()
                t2 = concurrency.thread(target=second, name="W2")
                t2.start()
                with cv:
                    cv.notify()  # must reach W2, never W1's retired ticket
                t2.join()
            r = explore(body, seed=seed)
            assert not r.errors and not r.findings
            return dict(outcomes)["second"]

        assert any(run_one(s) for s in range(6)), (
            "no seed delivered the second notify — retired tickets are "
            "absorbing live waiters' wakeups")

    def test_budget_bound_catches_livelock(self):
        from p2pnetwork_tpu.analysis.race import ScheduleBudgetExceeded

        def spin():
            ev = concurrency.event()
            while not ev.is_set():
                concurrency.sleep(0.01)
        with pytest.raises(ScheduleBudgetExceeded):
            explore(spin, seed=0, max_steps=500)


# ===================================================== detector internals


class TestDetector:
    def test_guarded_attr_inventory_matches_graftlint(self):
        from p2pnetwork_tpu.chaos.plane import ChaosPlane
        from p2pnetwork_tpu.crdt import CRDTNode
        from p2pnetwork_tpu.phi import PhiAccrualNode
        assert {"_arrivals", "_quarantined", "_quarantine_gen"} \
            <= set(guarded_attrs(PhiAccrualNode))
        assert "_crdts" in guarded_attrs(CRDTNode)
        assert {"_dead", "_cut", "_groups"} <= set(guarded_attrs(ChaosPlane))

    def test_watch_is_noop_outside_exploration(self):
        from p2pnetwork_tpu.chaos.plane import ChaosPlane
        plane = ChaosPlane(seed=0, registry=telemetry.Registry())
        assert watch(plane) is plane
        assert type(plane).__name__ == "ChaosPlane"  # class not swapped

    def test_watch_catches_unlocked_container_write(self):
        # Auto-tracking end to end: a class whose attr is lock-guarded in
        # one method and bare in another — the dynamic complement of
        # graftlint's lock-guard rule.
        class Box:
            def __init__(self):
                self._lk = concurrency.lock()
                self.items = {}

            def put_locked(self, k):
                with self._lk:
                    self.items[k] = 1

            def put_bare(self, k):
                self.items[k] = 1

        def body():
            box = watch(Box(), attrs={"items"})
            t1 = concurrency.thread(target=lambda: box.put_locked("a"))
            t2 = concurrency.thread(target=lambda: box.put_bare("b"))
            t1.start()
            t2.start()
            t1.join()
            t2.join()

        hits = [s for s in SEEDS if races(explore(body, seed=s))]
        assert hits, "unlocked container write never caught"

    def test_watch_catches_unlocked_deque_append(self):
        # deque-backed guarded state (EventLog._events, ChaosPlane._log)
        # must classify appends as writes — an unwrapped deque would
        # report reads only and the race class goes invisible.
        import collections

        class Log:
            def __init__(self):
                self._lk = concurrency.lock()
                self.events = collections.deque()

            def add_locked(self, x):
                with self._lk:
                    self.events.append(x)

            def add_bare(self, x):
                self.events.append(x)

        def body():
            log = watch(Log(), attrs={"events"})
            t1 = concurrency.thread(target=lambda: log.add_locked(1))
            t2 = concurrency.thread(target=lambda: log.add_bare(2))
            t1.start()
            t2.start()
            t1.join()
            t2.join()

        hits = [s for s in SEEDS if races(explore(body, seed=s))]
        assert hits, "unlocked deque append never caught"

    def test_shared_outside_exploration_is_a_plain_box(self):
        cell = Shared(7, label="x")
        assert cell.get() == 7
        cell.set(9)
        assert cell.get() == 9

    def test_vector_clock_epoch_ordering(self):
        det = Detector()
        det.on_spawn(None, 0)
        det.on_spawn(0, 1)
        # T0 writes, then T1 (which inherited T0's clock) reads: ordered.
        det.access(0, "v", True, ("f.py", 1))
        det.on_spawn(0, 2)  # re-sync: spawn edges tick the parent
        det.access(1, "v", False, ("f.py", 2))
        # T1's clock lacks T0's post-spawn writes only if the write came
        # after the spawn — write a second time from T0 and read again.
        det.access(0, "v", True, ("f.py", 3))
        det.access(1, "v", False, ("f.py", 4))
        assert any(f.rule == RACE_RULE for f in det.findings)


# ================================================== the live-tree battery


class TestLiveBattery:
    @pytest.mark.parametrize("name", builtin_names())
    def test_scenario_gates_clean(self, name):
        entry = SCENARIOS[name]
        try:
            entry.factory()
        except Exception as e:
            pytest.skip(f"{name} unavailable: {e}")
        for seed in range(3):
            r = explore(entry.factory(), seed=seed)
            assert not r.findings, (
                f"{name} seed {seed}:\n"
                + "\n".join(f.render() for f in r.findings))
            assert not r.errors, f"{name} seed {seed}: {r.errors}"

    def test_battery_counts_telemetry(self):
        reg = telemetry.Registry()
        findings, stats = run_battery(
            ["partition_heal"], seed=0, schedules=2, registry=reg)
        assert not findings
        assert reg.value("graftrace_schedules_total") == 2
        assert stats[0]["schedules"] == 2 and stats[0]["steps"] > 0

    def test_battery_counts_races(self):
        reg = telemetry.Registry()
        findings, _ = run_battery(
            ["fixture_lock_racy"], seed=0, schedules=4, registry=reg)
        assert findings
        assert reg.value("graftrace_races_total", rule=RACE_RULE) >= 1

    def test_battery_survives_a_livelocking_scenario(self):
        # One scenario blowing its step budget must become a structured
        # finding + stats row, never a traceback that abandons the rest.
        from p2pnetwork_tpu.analysis.race.scenarios import scenario

        def spin():
            ev = concurrency.event()
            while not ev.is_set():
                concurrency.sleep(0)

        @scenario("fixture_livelock", "spins forever", builtin=False)
        def _fixture_livelock():
            return spin

        reg = telemetry.Registry()
        findings, stats = run_battery(
            ["fixture_livelock", "partition_heal"], seed=0, schedules=1,
            max_steps=300, registry=reg)
        live = next(s for s in stats if s["scenario"] == "fixture_livelock")
        heal = next(s for s in stats if s["scenario"] == "partition_heal")
        assert live["errors"] and "ScheduleBudgetExceeded" in \
            live["errors"][0]["error"]
        assert any(f.rule == "graftrace-error" for f in findings)
        assert heal["schedules"] == 1  # the battery kept going


# ================================================================= the CLI


class TestCLI:
    def test_clean_battery_exits_zero(self, capsys):
        rc = graftrace_main(["--scenario", "partition_heal",
                             "--schedules", "2"])
        out = capsys.readouterr().out
        assert rc == 0 and "clean" in out

    def test_racy_scenario_exits_nonzero(self, capsys):
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--scenario", "fixture_lock_racy",
                             "--schedules", "3"])
        out = capsys.readouterr().out
        assert rc == 1
        assert RACE_RULE in out

    def test_json_output(self, capsys):
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--scenario", "fixture_lock_racy",
                             "--schedules", "2", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1 and doc["ok"] is False
        assert doc["findings"][0]["rule"] == RACE_RULE
        assert doc["findings"][0]["file"].endswith(
            "graftrace_fixtures.py")

    def test_trace_dir_and_replay_roundtrip(self, tmp_path, capsys):
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--scenario", "fixture_lock_racy",
                             "--schedules", "2", "--seed", "1",
                             "--trace-dir", str(tmp_path)])
        assert rc == 1
        capsys.readouterr()
        traces = sorted(tmp_path.glob("fixture_lock_racy_s*.json"))
        assert traces, "no replay file written for a failing schedule"
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--replay", str(traces[0])])
        out = capsys.readouterr().out
        assert rc == 1  # identical replay, findings still present
        assert "byte-identical" in out

    def test_replay_divergence_is_exit_2(self, tmp_path, capsys):
        r = explore(fx.lock_racy, seed=2)
        path = str(tmp_path / "tampered.json")
        write_replay(path, "fixture_lock_racy", r)
        with open(path) as f:
            doc = json.load(f)
        doc["trace"][4] = ["ghost", "acquire", "lock99"]
        with open(path, "w") as f:
            json.dump(doc, f)
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--replay", path])
        assert rc == 2
        assert "DIVERGED" in capsys.readouterr().err

    def test_unknown_scenario_exits_2(self, capsys):
        rc = graftrace_main(["--scenario", "no_such_scenario"])
        assert rc == 2

    def test_broken_scenarios_file_exits_2(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("def broken(:\n")
        rc = graftrace_main(["--scenarios-from", str(bad)])
        assert rc == 2
        assert "cannot load" in capsys.readouterr().err

    def test_replay_restores_recorded_step_budget(self, tmp_path):
        r = explore(fx.lock_clean, seed=0, max_steps=123_456)
        path = write_replay(str(tmp_path / "b.json"), "x", r)
        assert load_replay(path)["max_steps"] == 123_456

    def test_replay_of_error_only_schedule_exits_1(self, tmp_path, capsys):
        # A schedule gated (and recorded) for task ERRORS must fail its
        # replay too, not pass as "clean, byte-identical".
        from p2pnetwork_tpu.analysis.race.scenarios import scenario

        def crashing():
            def boom():
                raise ValueError("scenario crash")
            t = concurrency.thread(target=boom, name="B")
            t.start()
            t.join()

        @scenario("fixture_error_only", "crashes, no races",
                  builtin=False)
        def _fixture_error_only():
            return crashing

        r = explore(crashing, seed=0)
        assert r.errors and not r.findings
        path = str(tmp_path / "err.json")
        write_replay(path, "fixture_error_only", r)
        rc = graftrace_main(["--replay", path])
        out = capsys.readouterr().out
        assert rc == 1
        assert "ValueError" in out

    def test_distinct_unlabeled_shared_cells_do_not_alias(self):
        # Two unlabeled cells, each guarded by its own lock: a
        # label-aliasing detector would fabricate a race between them.
        def body():
            c1, c2 = Shared(0), Shared(0)
            l1, l2 = concurrency.lock(), concurrency.lock()

            def a():
                with l1:
                    c1.set(c1.get() + 1)

            def b():
                with l2:
                    c2.set(c2.get() + 1)
            fx._pair(a, b)
        for seed in SEEDS:
            r = explore(body, seed=seed)
            assert not r.findings, (
                f"seed {seed}: " + r.findings[0].render())

    def test_list_scenarios(self, capsys):
        rc = graftrace_main(["--list-scenarios"])
        out = capsys.readouterr().out
        assert rc == 0
        for name in builtin_names():
            assert name in out

    def test_baseline_absorbs_then_write_baseline_roundtrip(self, tmp_path,
                                                            capsys):
        bl = tmp_path / "bl.json"
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--scenario", "fixture_lock_racy",
                             "--schedules", "2",
                             "--baseline", str(bl), "--write-baseline"])
        assert rc == 0 and bl.exists()
        capsys.readouterr()
        rc = graftrace_main(["--scenarios-from", FIXTURE_FILE,
                             "--scenario", "fixture_lock_racy",
                             "--schedules", "2", "--baseline", str(bl)])
        out = capsys.readouterr().out
        assert rc == 0 and "baselined" in out

    def test_checked_in_baseline_is_empty(self):
        # Races found during development are FIXED in this PR, not
        # baselined — the acceptance criterion, pinned.
        from p2pnetwork_tpu.analysis.race.__main__ import (
            default_baseline_path,
        )
        doc = json.load(open(default_baseline_path()))
        assert doc["findings"] == []

    @pytest.mark.slow
    def test_console_entry_runs_the_full_gate(self):
        proc = subprocess.run(
            [sys.executable, "-m", "p2pnetwork_tpu.analysis.race",
             "--schedules", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "clean" in proc.stdout
