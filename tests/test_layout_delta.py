"""Incremental graph builds (GraphDelta / apply_delta), IO-aware
reordering (sim/layout.py), the content-addressed layout cache
(sim/layoutcache.py), and build-phase telemetry.

The load-bearing claim is BIT-IDENTITY: ``apply_delta`` must produce
exactly the arrays a from-scratch ``from_edges`` on the merged edge list
would — across weighted edges, ``max_degree``-capped tables, kernel
layouts, the source CSR, both the native and ``force_fallback()`` host
paths, and both donation modes. The ``buildperf``-marked ratchet then
enforces the point of it all: a 1%-edge delta at 1M-edge scale must beat
the full rebuild by >= 10x on CPU (ratio-based — no wall-clock
thresholds, no TPU).
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu import native  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.sim import layout, layoutcache  # noqa: E402


def unique_edges(rng, n, target):
    """~``target`` unique directed (s != r) pairs, deterministic."""
    s = rng.integers(0, n, target * 3).astype(np.int32)
    r = rng.integers(0, n, target * 3).astype(np.int32)
    keep = s != r
    keys = np.unique(s[keep].astype(np.int64) * n + r[keep])[:target]
    return (keys // n).astype(np.int32), (keys % n).astype(np.int32), keys


def split_delta(rng, s, r, keys, n, n_rem, n_add, weighted=False):
    """A removal batch sampled from existing edges plus an add batch of
    fresh unique pairs (absent from ``keys``)."""
    rem_idx = (rng.choice(s.size, n_rem, replace=False) if n_rem
               else np.zeros(0, dtype=np.int64))
    cs = rng.integers(0, n, n_add * 3 + 8).astype(np.int32)
    cr = rng.integers(0, n, n_add * 3 + 8).astype(np.int32)
    ck = cs != cr
    ckeys = np.setdiff1d(
        np.unique(cs[ck].astype(np.int64) * n + cr[ck]), keys)[:n_add]
    add_s = (ckeys // n).astype(np.int32)
    add_r = (ckeys % n).astype(np.int32)
    kw = dict(add_senders=add_s, add_receivers=add_r,
              remove_senders=s[rem_idx], remove_receivers=r[rem_idx])
    if weighted:
        kw["add_weights"] = rng.random(add_s.size).astype(np.float32)
    return G.GraphDelta(**kw), rem_idx


def merged_reference_edges(g, rem_s, rem_r, add_s, add_r):
    """The from-scratch equivalent edge list: the base's live sorted
    edges minus the removed pairs, with the adds appended."""
    e = g.n_edges
    bs = np.asarray(g.senders)[:e]
    br = np.asarray(g.receivers)[:e]
    n_pad = g.n_nodes_padded
    rem_keys = np.sort(rem_s.astype(np.int64) * n_pad + rem_r)
    bk = bs.astype(np.int64) * n_pad + br
    pos = np.searchsorted(rem_keys, bk)
    hit = np.zeros(e, dtype=bool)
    if rem_keys.size:
        hit = rem_keys[np.minimum(pos, rem_keys.size - 1)] == bk
    keep = np.asarray(g.edge_mask)[:e] & ~hit
    return (np.concatenate([bs[keep], add_s]),
            np.concatenate([br[keep], add_r]), keep)


_STATIC_FIELDS = ("n_nodes", "n_edges", "neighbors_complete",
                  "max_degree_cap", "max_in_span", "max_out_span")
_ARRAY_FIELDS = ("senders", "receivers", "edge_mask", "node_mask",
                 "in_degree", "out_degree", "neighbors", "neighbor_mask",
                 "src_eid", "src_offsets", "edge_weight", "neighbor_weight",
                 "layout_perm", "layout_inv")


def assert_graphs_bit_identical(a, b, ctx=""):
    for f in _STATIC_FIELDS:
        assert getattr(a, f) == getattr(b, f), f"{ctx}: static {f}"
    for f in _ARRAY_FIELDS:
        av, bv = getattr(a, f), getattr(b, f)
        assert (av is None) == (bv is None), f"{ctx}: {f} presence"
        if av is not None:
            av, bv = np.asarray(av), np.asarray(bv)
            assert av.shape == bv.shape, f"{ctx}: {f} shape"
            assert (av == bv).all(), f"{ctx}: {f} values"
    for rep, fields in (("blocked", ("src", "local_dst", "mask")),
                        ("skew", ("src", "mask", "owner", "start"))):
        ra, rb = getattr(a, rep), getattr(b, rep)
        assert (ra is None) == (rb is None), f"{ctx}: {rep} presence"
        if ra is not None:
            for f in fields:
                assert (np.asarray(getattr(ra, f))
                        == np.asarray(getattr(rb, f))).all(), \
                    f"{ctx}: {rep}.{f}"
    ha, hb = a.hybrid, b.hybrid
    assert (ha is None) == (hb is None), f"{ctx}: hybrid presence"
    if ha is not None:
        assert ha.offsets == hb.offsets and ha.n == hb.n
        assert (np.asarray(ha.masks) == np.asarray(hb.masks)).all()
        assert (ha.remainder is None) == (hb.remainder is None)
        if ha.remainder is not None:
            for f in ("src", "local_dst", "mask"):
                assert (np.asarray(getattr(ha.remainder, f))
                        == np.asarray(getattr(hb.remainder, f))).all()


@pytest.fixture(params=["native", "fallback"])
def host_path(request):
    if request.param == "fallback":
        native.force_fallback(True)
        yield "fallback"
        native.force_fallback(False)
    else:
        if not native.available():
            pytest.skip("no native library on this host")
        yield "native"


class TestGraphDelta:
    def test_shape_mismatch_rejected(self):
        with pytest.raises(ValueError, match="shape mismatch"):
            G.GraphDelta(add_senders=[1, 2], add_receivers=[3])
        with pytest.raises(ValueError, match="shape mismatch"):
            G.GraphDelta(remove_senders=[1], remove_receivers=[])
        with pytest.raises(ValueError, match="add_weights"):
            G.GraphDelta(add_senders=[1], add_receivers=[2],
                         add_weights=[0.5, 0.6])

    def test_undirected_stores_both_directions(self):
        d = G.GraphDelta.undirected(add_senders=[1], add_receivers=[2],
                                    add_weights=[0.5],
                                    remove_senders=[3], remove_receivers=[4])
        assert d.add_senders.tolist() == [1, 2]
        assert d.add_receivers.tolist() == [2, 1]
        assert d.add_weights.tolist() == [0.5, 0.5]
        assert d.remove_senders.tolist() == [3, 4]
        assert d.remove_receivers.tolist() == [4, 3]
        assert d.n_adds == 2 and d.n_removes == 2


class TestApplyDeltaEquivalence:
    """Seeded property test: apply_delta == from-scratch from_edges on the
    merged edge list, bit for bit, across configs and host paths."""

    @pytest.mark.parametrize("config", ["plain", "weighted", "capped",
                                        "csr", "no_table"])
    @pytest.mark.parametrize("donate", [False, True])
    def test_random_batches_match_rebuild(self, host_path, config, donate):
        for seed in range(4):
            rng = np.random.default_rng(seed)
            n = int(rng.integers(20, 250))
            s, r, keys = unique_edges(rng, n, int(rng.integers(4, 900)))
            kw = {}
            weighted = config == "weighted"
            if config == "capped":
                kw["max_degree"] = 3
            if config in ("csr", "weighted"):
                kw["source_csr"] = True
            if config == "no_table":
                kw["build_neighbor_table"] = False
                kw["source_csr"] = True
            if weighted:
                kw["weights"] = rng.random(s.size).astype(np.float32)
            g = G.from_edges(s, r, n, **kw)
            delta, rem_idx = split_delta(
                rng, s, r, keys, n, n_rem=int(rng.integers(0, s.size + 1)),
                n_add=int(rng.integers(0, 60)), weighted=weighted)
            ref_s, ref_r, kept = merged_reference_edges(
                g, delta.remove_senders, delta.remove_receivers,
                delta.add_senders, delta.add_receivers)
            rkw = dict(kw)
            if weighted:
                wbase = np.asarray(g.edge_weight)[:g.n_edges]
                rkw["weights"] = np.concatenate(
                    [wbase[kept], delta.add_weights])
            got = g.apply_delta(delta, donate=donate)
            want = G.from_edges(ref_s, ref_r, n, **rkw)
            assert_graphs_bit_identical(
                got, want, f"{config}/seed{seed}/donate={donate}")

    def test_sequential_deltas_compose(self, host_path):
        rng = np.random.default_rng(11)
        n = 120
        s, r, keys = unique_edges(rng, n, 500)
        g = G.from_edges(s, r, n, source_csr=True)
        d1, _ = split_delta(rng, s, r, keys, n, n_rem=40, n_add=30)
        g1 = g.apply_delta(d1)
        e1 = g1.n_edges
        s1 = np.asarray(g1.senders)[:e1]
        r1 = np.asarray(g1.receivers)[:e1]
        k1 = s1.astype(np.int64) * n + r1
        d2, _ = split_delta(rng, s1, r1, np.sort(k1), n, n_rem=25, n_add=20)
        g2 = g1.apply_delta(d2)
        ref_s, ref_r, _ = merged_reference_edges(
            g1, d2.remove_senders, d2.remove_receivers,
            d2.add_senders, d2.add_receivers)
        want = G.from_edges(ref_s, ref_r, n, source_csr=True)
        assert_graphs_bit_identical(g2, want, "sequential")

    def test_layout_representations_rebuilt(self, host_path):
        rng = np.random.default_rng(5)
        n = 96
        s, r, keys = unique_edges(rng, n, 400)
        g = G.from_edges(s, r, n, blocked=True, hybrid=True, skew_table=True,
                         source_csr=True)
        delta, _ = split_delta(rng, s, r, keys, n, n_rem=30, n_add=25)
        ref_s, ref_r, _ = merged_reference_edges(
            g, delta.remove_senders, delta.remove_receivers,
            delta.add_senders, delta.add_receivers)
        got = g.apply_delta(delta)
        # The rebuilt skew table keeps the BASE's row width (preserving
        # tuned layouts) rather than re-auto-picking on the merged
        # histogram — the reference pins the same width.
        want = G.from_edges(ref_s, ref_r, n, blocked=True, hybrid=True,
                            skew_table=True, skew_width=g.skew.width,
                            source_csr=True)
        assert_graphs_bit_identical(got, want, "layouts")

    def test_layout_rebuild_keeps_tuned_params(self, host_path):
        # Regression (review): a delta used to rebuild blocked/hybrid/skew
        # at DEFAULT params, silently reverting user-tuned tile sizes.
        rng = np.random.default_rng(9)
        n = 128
        s, r, keys = unique_edges(rng, n, 500)
        g = G.from_edges(s, r, n).with_blocked(block=256)
        g = g.with_hybrid(block=256).with_skew_table(width=16)
        delta, _ = split_delta(rng, s, r, keys, n, n_rem=20, n_add=15)
        g2 = g.apply_delta(delta)
        assert g2.blocked.block == 256
        assert g2.skew.width == 16
        if g.hybrid.remainder is not None:
            assert g2.hybrid.remainder.block == 256

    def test_delta_keeps_base_edge_pad_multiple(self, host_path):
        # Regression (review): a base built with a coarse pad multiple
        # (to hold shapes stable across churn) used to snap back to the
        # 128 default on the first delta, recompiling every jitted
        # consumer. The recorded multiple now carries through deltas,
        # consolidation, and save_graph.
        rng = np.random.default_rng(13)
        n = 100
        s, r, keys = unique_edges(rng, n, 300)
        g = G.from_edges(s, r, n, edge_pad_multiple=1024, source_csr=True)
        assert g.edge_pad_multiple == 1024
        delta, _ = split_delta(rng, s, r, keys, n, n_rem=10, n_add=10)
        g2 = g.apply_delta(delta)
        assert g2.n_edges_padded == g.n_edges_padded == 1024
        ref_s, ref_r, _ = merged_reference_edges(
            g, delta.remove_senders, delta.remove_receivers,
            delta.add_senders, delta.add_receivers)
        want = G.from_edges(ref_s, ref_r, n, edge_pad_multiple=1024,
                            source_csr=True)
        assert_graphs_bit_identical(g2, want, "pad-multiple")
        from p2pnetwork_tpu.sim import topology

        g3 = topology.consolidate(topology.with_capacity(g2, extra_edges=8))
        assert g3.n_edges_padded % 1024 == 0

    def test_unbitten_max_degree_cap_still_bounds_churn(self, host_path):
        # Regression (review): a cap WIDER than the build-time max degree
        # leaves the table complete — but must still bound it when a
        # churn delta grows a hub past the cap, exactly as the
        # from-scratch rebuild with the same max_degree would.
        n = 50
        base = np.arange(1, 9, dtype=np.int32)  # node 0 has in-degree 8
        g = G.from_edges(base, np.zeros(8, np.int32), n, max_degree=12)
        assert g.neighbors_complete and g.max_degree == 8
        assert g.max_degree_cap == 12
        add_s = np.arange(9, 39, dtype=np.int32)  # +30 in-edges on node 0
        delta = G.GraphDelta(add_senders=add_s,
                             add_receivers=np.zeros(30, np.int32))
        got = g.apply_delta(delta)
        want = G.from_edges(np.concatenate([base, add_s]),
                            np.zeros(38, np.int32), n, max_degree=12)
        assert got.max_degree == 12 and not got.neighbors_complete
        assert_graphs_bit_identical(got, want, "unbitten-cap")
        # consolidate honors the recorded cap the same way
        from p2pnetwork_tpu.sim import topology

        g_dyn = topology.with_capacity(got, extra_edges=128)
        g_cons = topology.consolidate(g_dyn)
        assert g_cons.max_degree == 12 and g_cons.max_degree_cap == 12

    def test_remove_all_edges(self, host_path):
        g = G.ring(10)
        e = g.n_edges
        s = np.asarray(g.senders)[:e]
        r = np.asarray(g.receivers)[:e]
        g2 = g.apply_delta(G.GraphDelta(remove_senders=s, remove_receivers=r))
        assert g2.n_edges == 0
        want = G.from_edges(np.zeros(0), np.zeros(0), 10)
        assert_graphs_bit_identical(g2, want, "remove-all")

    def test_empty_delta_is_identity_rebuild(self, host_path):
        g = G.watts_strogatz(100, 4, 0.2, seed=1, source_csr=True)
        g2 = g.apply_delta(G.GraphDelta())
        assert_graphs_bit_identical(g2, g, "empty")

    def test_propagation_matches_after_delta(self, host_path):
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        rng = np.random.default_rng(3)
        n = 200
        s, r, keys = unique_edges(rng, n, 800)
        g = G.from_edges(s, r, n)
        delta, _ = split_delta(rng, s, r, keys, n, n_rem=50, n_add=50)
        ref_s, ref_r, _ = merged_reference_edges(
            g, delta.remove_senders, delta.remove_receivers,
            delta.add_senders, delta.add_receivers)
        key = jax.random.key(0)
        _, out_delta = engine.run_until_coverage(
            g.apply_delta(delta), Flood(source=0), key, max_rounds=32)
        _, out_ref = engine.run_until_coverage(
            G.from_edges(ref_s, ref_r, n), Flood(source=0), key,
            max_rounds=32)
        assert out_delta == out_ref

    def test_donate_invalidates_base_table_buffers(self):
        g = G.watts_strogatz(200, 4, 0.1, seed=2)
        delta = G.GraphDelta(add_senders=[0], add_receivers=[5])
        g2 = g.apply_delta(delta, donate=True)
        assert g2.neighbors is not None
        # The donor's table buffer was consumed in place (engine-style
        # donation contract); the result's buffers are live.
        assert g.neighbors.is_deleted()
        assert not g2.neighbors.is_deleted()

    def test_dynamic_region_rides_along(self, host_path):
        from p2pnetwork_tpu.sim import topology

        g = G.ring(20)
        g = topology.with_capacity(g, extra_edges=8)
        g = topology.connect(g, [0], [10])
        delta = G.GraphDelta.undirected(add_senders=[2], add_receivers=[7])
        g2 = g.apply_delta(delta)
        assert (np.asarray(g2.dyn_mask) == np.asarray(g.dyn_mask)).all()
        assert (np.asarray(g2.dyn_senders)
                == np.asarray(g.dyn_senders)).all()
        # in_degree keeps counting the live dynamic links on top of the
        # updated static edges (both directions of the new static pair).
        assert int(g2.in_degree[10]) == int(g.in_degree[10])
        assert int(g2.in_degree[2]) == int(g.in_degree[2]) + 1
        assert int(g2.in_degree[7]) == int(g.in_degree[7]) + 1

    def test_absent_removal_raises(self):
        g = G.ring(10)
        with pytest.raises(ValueError, match="match no live edge"):
            g.apply_delta(G.GraphDelta(remove_senders=[0],
                                       remove_receivers=[5]))

    def test_add_out_of_range_raises(self):
        g = G.ring(10)
        with pytest.raises(ValueError, match="out of range"):
            g.apply_delta(G.GraphDelta(add_senders=[0],
                                       add_receivers=[10]))

    def test_weight_contract_enforced(self):
        gw = G.ring(10)
        gw = gw.with_weights(lambda s, r: (s + r).astype(np.float32))
        with pytest.raises(ValueError, match="need add_weights"):
            gw.apply_delta(G.GraphDelta(add_senders=[0], add_receivers=[3]))
        g = G.ring(10)
        with pytest.raises(ValueError, match="unweighted"):
            g.apply_delta(G.GraphDelta(add_senders=[0], add_receivers=[3],
                                       add_weights=[1.0]))

    def test_removed_then_readded_pair(self, host_path):
        # A churn storm frequently re-adds a just-dropped link; both
        # operations in one batch must behave like the merged rebuild.
        g = G.ring(12)
        e = g.n_edges
        s0 = int(np.asarray(g.senders)[0])
        r0 = int(np.asarray(g.receivers)[0])
        delta = G.GraphDelta(add_senders=[s0], add_receivers=[r0],
                             remove_senders=[s0], remove_receivers=[r0])
        g2 = g.apply_delta(delta)
        assert g2.n_edges == e
        bs = np.asarray(g.senders)[:e]
        br = np.asarray(g.receivers)[:e]
        keep = ~((bs == s0) & (br == r0))
        want = G.from_edges(np.concatenate([bs[keep], [s0]]),
                            np.concatenate([br[keep], [r0]]), 12)
        assert_graphs_bit_identical(g2, want, "re-add")


class TestReorder:
    def test_permutations_are_bijections(self):
        rng = np.random.default_rng(0)
        s, r, _ = unique_edges(rng, 300, 900)
        for strat in layout.STRATEGIES:
            perm = layout.node_permutation(s, r, 300, strategy=strat)
            assert np.array_equal(np.sort(perm), np.arange(300))
            inv = layout.invert_permutation(perm)
            assert np.array_equal(perm[inv], np.arange(300))

    def test_unknown_strategy_rejected(self):
        with pytest.raises(ValueError, match="unknown reorder strategy"):
            G.from_edges([0], [1], 4, reorder="zorder")

    def test_degree_permutation_buckets_by_degree(self):
        g = G.barabasi_albert(400, 3, seed=1)
        e = g.n_edges
        s = np.asarray(g.senders)[:e]
        r = np.asarray(g.receivers)[:e]
        perm = layout.degree_permutation(s, r, 400)
        order = layout.invert_permutation(perm)
        deg = np.bincount(s, minlength=400) + np.bincount(r, minlength=400)
        assert (np.diff(deg[order]) >= 0).all()

    def test_rcm_improves_edge_locality(self):
        plain = G.erdos_renyi(400, 0.01, seed=0)
        rcm = G.erdos_renyi(400, 0.01, seed=0, reorder="rcm")

        def mean_span(g):
            em = np.asarray(g.edge_mask)
            s = np.asarray(g.senders)[em].astype(np.int64)
            r = np.asarray(g.receivers)[em].astype(np.int64)
            return np.abs(s - r).mean()

        assert mean_span(rcm) < mean_span(plain)

    @pytest.mark.parametrize("strat", ["degree", "rcm"])
    def test_flood_parity_through_the_mapping(self, strat):
        from p2pnetwork_tpu.models.flood import Flood
        from p2pnetwork_tpu.sim import engine

        g_plain = G.watts_strogatz(400, 6, 0.2, seed=3)
        g_re = G.watts_strogatz(400, 6, 0.2, seed=3, reorder=strat)
        perm = np.asarray(g_re.layout_perm)
        src = 17
        key = jax.random.key(0)
        _, out0 = engine.run_until_coverage(
            g_plain, Flood(source=src), key, max_rounds=64)
        _, out1 = engine.run_until_coverage(
            g_re, Flood(source=int(perm[src])), key, max_rounds=64)
        # Summaries are invariant under the relabeling...
        assert out0 == out1
        # ...and per-node results permute back exactly.
        st0, _ = engine.run(g_plain, Flood(source=src), key,
                            int(out0["rounds"]))
        st1, _ = engine.run(g_re, Flood(source=int(perm[src])), key,
                            int(out0["rounds"]))
        seen0 = np.asarray(st0.seen)
        seen1 = layout.to_original_order(np.asarray(st1.seen), g_re)
        assert (seen0 == seen1).all()

    def test_gossip_mean_preserved_under_permutation(self):
        import dataclasses as dc

        from p2pnetwork_tpu.models.gossip import Gossip, GossipState
        from p2pnetwork_tpu.sim import engine

        g_plain = G.watts_strogatz(256, 6, 0.1, seed=5)
        g_re = G.watts_strogatz(256, 6, 0.1, seed=5, reorder="rcm")
        rng = np.random.default_rng(0)
        vals = (rng.standard_normal(g_plain.n_nodes_padded)
                .astype(np.float32) * np.asarray(g_plain.node_mask))
        key = jax.random.key(1)
        proto = Gossip()
        st0 = GossipState(values=jax.numpy.asarray(vals))
        st1 = GossipState(values=jax.numpy.asarray(
            layout.to_layout_order(vals, g_re)))
        out0, stats0 = engine.run_from(g_plain, proto, st0, key, 30)
        out1, stats1 = engine.run_from(g_re, proto, st1, key, 30)
        target = vals.sum() / g_plain.n_nodes
        # Randomized trajectories differ per labeling, but the protocol's
        # summary invariants survive the permutation: both runs mix toward
        # the same population mean with comparably shrinking variance.
        for stats, g in ((stats0, g_plain), (stats1, g_re)):
            assert abs(float(stats["mean"][-1]) - target) < 0.25
            assert float(stats["variance"][-1]) < 0.5 * float(
                stats["variance"][0])

    def test_to_layout_roundtrip_and_plain_graph_identity(self):
        g_re = G.ring(50, reorder="degree")
        x = np.arange(g_re.n_nodes_padded)
        back = layout.to_original_order(layout.to_layout_order(x, g_re), g_re)
        assert (back == x).all()
        g = G.ring(50)
        assert layout.to_original_order(x, g) is x

    def test_mapping_helpers_keep_device_arrays_on_device(self):
        # Regression (review): a jax input must gather with the
        # device-resident permutation (no per-call device->host pull of
        # an i32[N_pad] array inside monitoring loops).
        g_re = G.ring(50, reorder="degree")
        x = jax.numpy.arange(g_re.n_nodes_padded)
        out = layout.to_original_order(x, g_re)
        assert isinstance(out, jax.Array)
        assert (np.asarray(out)
                == np.asarray(x)[np.asarray(g_re.layout_perm)]).all()

    def test_reordered_graph_roundtrips_through_save_graph(self, tmp_path):
        from p2pnetwork_tpu.sim import checkpoint as ckpt

        g = G.watts_strogatz(128, 4, 0.1, seed=0, reorder="rcm",
                             source_csr=True)
        path = str(tmp_path / "g.npz")
        ckpt.save_graph(path, g)
        g2 = ckpt.load_graph(path)
        assert (np.asarray(g2.layout_perm)
                == np.asarray(g.layout_perm)).all()
        assert (np.asarray(g2.layout_inv) == np.asarray(g.layout_inv)).all()

    def test_delta_carries_the_permutation(self):
        g = G.watts_strogatz(128, 4, 0.1, seed=0, reorder="degree")
        g2 = g.apply_delta(G.GraphDelta(add_senders=[0], add_receivers=[9]))
        assert (np.asarray(g2.layout_perm)
                == np.asarray(g.layout_perm)).all()


class TestLayoutCache:
    def test_build_once_then_hit(self, tmp_path):
        calls = []

        def build():
            calls.append(1)
            return G.ring(64)

        events = []
        g1, _, hit1 = layoutcache.cached_graph(
            "ring64", build, cache_dir=str(tmp_path), params={"n": 64},
            on_miss=lambda *a: events.append(a))
        g2, _, hit2 = layoutcache.cached_graph(
            "ring64", build, cache_dir=str(tmp_path), params={"n": 64},
            on_miss=lambda *a: events.append(a))
        assert (not hit1) and hit2 and len(calls) == 1
        assert events[0][0] == "missing"
        assert (np.asarray(g1.senders) == np.asarray(g2.senders)).all()

    def test_corrupt_entry_reported_and_rebuilt(self, tmp_path):
        path = layoutcache.entry_path("bad", cache_dir=str(tmp_path))
        os.makedirs(str(tmp_path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(b"not an npz")
        events = []
        g, _, hit = layoutcache.cached_graph(
            "bad", lambda: G.ring(16), cache_dir=str(tmp_path),
            on_miss=lambda reason, p, err: events.append((reason, p, err)))
        assert not hit and g.n_nodes == 16
        assert events[0][0] == "corrupt" and events[0][2]

    def test_disabled_reports_and_skips_store(self, tmp_path):
        events = []
        _, _, hit = layoutcache.cached_graph(
            "off", lambda: G.ring(16), cache_dir=str(tmp_path),
            enabled=False, on_miss=lambda *a: events.append(a))
        assert not hit and events[0][0] == "disabled"
        # a disabled cache computes no fingerprint/path at all
        assert events[0][1] is None
        assert not any(f.endswith(".npz") for f in os.listdir(tmp_path))

    def test_fingerprint_tolerates_absent_default_source(self, monkeypatch):
        # Regression (review): a .py-only install without graphcore.cpp
        # must degrade (absence fingerprinted), never crash the build.
        monkeypatch.setattr(
            layoutcache, "DEFAULT_SOURCES",
            layoutcache.DEFAULT_SOURCES + ("native/not_shipped.cpp",))
        a = layoutcache.fingerprint()
        assert a and a != layoutcache.fingerprint(params={"x": 1})

    def test_params_change_the_fingerprint(self):
        base = layoutcache.fingerprint(params={"n": 64})
        assert base != layoutcache.fingerprint(params={"n": 128})
        assert base != layoutcache.fingerprint(
            params={"n": 64, "reorder": "rcm"})
        assert base == layoutcache.fingerprint(params={"n": 64})

    def test_source_edit_changes_the_fingerprint(self, tmp_path):
        extra = tmp_path / "caller.py"
        extra.write_text("k = 10\n")
        a = layoutcache.fingerprint(extra_sources=(str(extra),))
        extra.write_text("k = 12\n")
        b = layoutcache.fingerprint(extra_sources=(str(extra),))
        assert a != b

    def test_stale_fingerprint_entry_ignored(self, tmp_path):
        g, _, _ = layoutcache.cached_graph(
            "g", lambda: G.ring(32), cache_dir=str(tmp_path))
        entry = next(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
        fp = layoutcache.fingerprint()
        assert fp in entry
        stale = entry.replace(fp, "0" * len(fp))
        os.rename(os.path.join(tmp_path, entry),
                  os.path.join(tmp_path, stale))
        calls = []

        def build():
            calls.append(1)
            return G.ring(32)

        _, _, hit = layoutcache.cached_graph("g", build,
                                             cache_dir=str(tmp_path))
        assert not hit and calls  # the stale entry must not be loaded

    def test_clear_removes_entries(self, tmp_path):
        layoutcache.cached_graph("a", lambda: G.ring(16),
                                 cache_dir=str(tmp_path))
        layoutcache.cached_graph("b", lambda: G.ring(16),
                                 cache_dir=str(tmp_path))
        assert layoutcache.clear(str(tmp_path)) == 2
        assert layoutcache.clear(str(tmp_path)) == 0

    def test_default_sources_cover_the_stale_cache_bug(self):
        # The regression this store fixes: the old bench-private
        # fingerprint omitted the native kernels and the topology
        # generators, silently reusing stale caches after edits there.
        for rel in ("native/graphcore.cpp", "native/__init__.py",
                    "sim/topology.py", "sim/layout.py"):
            assert rel in layoutcache.DEFAULT_SOURCES


class TestScatterBuckets:
    def test_pow2_pad_buckets(self):
        assert [G._pow2_pad(k) for k in (1, 2, 3, 5, 8, 9)] == \
            [1, 2, 4, 8, 8, 16]

    def test_pad_repeat_last(self):
        a = np.array([[1, 2], [3, 4]])
        p = G._pad_repeat_last(a, 4)
        assert p.shape == (4, 2) and (p[2] == p[1]).all() \
            and (p[3] == p[1]).all()
        assert G._pad_repeat_last(a, 2) is a


class TestBuildPhases:
    def test_from_edges_records_phases(self):
        G.watts_strogatz(300, 4, 0.1, seed=1, source_csr=True, hybrid=True)
        ph = G.last_build_phases()
        for key in ("dedup_s", "sort_s", "neighbor_table_s", "source_csr_s",
                    "layouts_s"):
            assert key in ph and ph[key] >= 0
        # A plain build resets the record (no stale CSR/layout entries).
        G.ring(32)
        ph2 = G.last_build_phases()
        assert "source_csr_s" not in ph2 and "sort_s" in ph2

    def test_reorder_phase_recorded(self):
        G.watts_strogatz(200, 4, 0.1, seed=0, reorder="rcm")
        assert "reorder_s" in G.last_build_phases()

    def test_apply_delta_records_phases(self):
        g = G.watts_strogatz(200, 4, 0.1, seed=0, source_csr=True)
        g.apply_delta(G.GraphDelta(add_senders=[0], add_receivers=[9]))
        ph = G.last_build_phases()
        for key in ("delta_sort_s", "delta_merge_s", "delta_degrees_s",
                    "neighbor_table_s", "source_csr_s"):
            assert key in ph

    def test_phase_counter_in_registry(self):
        from p2pnetwork_tpu import telemetry

        G.ring(64)
        snap = telemetry.default_registry().snapshot()
        samples = snap["sim_graph_build_seconds_total"]["samples"]
        assert any(s["labels"]["phase"] == "sort" for s in samples)


@pytest.mark.buildperf
class TestBuildPerfRatchet:
    """The CI-enforced perf claim: a 1%-edge delta at 1M-edge scale beats
    the from-scratch rebuild >= 10x on CPU. Ratio-based — both sides run
    on the same host moments apart, so machine speed cancels out."""

    def test_delta_apply_at_least_10x_faster_than_rebuild(self):
        import time

        if not native.available():
            pytest.skip("perf ratchet needs the native host kernels")
        N = 1_000_000  # bench-headline node scale
        E = 1_000_000  # the pinned ratchet size
        rng = np.random.default_rng(0)
        s, r, keys = unique_edges(rng, N, E)
        assert s.size == E
        D = E // 200  # 0.5% removes + 0.5% adds = 1% churn
        rem_idx = rng.choice(E, D, replace=False)
        # Adds target currently-low-in-degree receivers so the table width
        # (= max in-degree) stays put and the in-place donation fast path
        # is exercised — the steady-state churn shape.
        deg = np.bincount(r, minlength=N)
        low = np.flatnonzero(deg <= np.median(deg[deg > 0]))
        add_r = rng.choice(low, D).astype(np.int32)
        add_s = rng.integers(0, N, D).astype(np.int32)
        loops = add_s == add_r
        add_s[loops] = (add_r[loops] + 1) % N
        delta = G.GraphDelta(add_senders=add_s, add_receivers=add_r,
                             remove_senders=s[rem_idx],
                             remove_receivers=r[rem_idx])

        base = G.from_edges(s, r, N, source_csr=True)
        ref_s, ref_r, _ = merged_reference_edges(
            base, delta.remove_senders, delta.remove_receivers,
            delta.add_senders, delta.add_receivers)

        t_full = np.inf
        for _ in range(2):
            t0 = time.perf_counter()
            want = G.from_edges(ref_s, ref_r, N, source_csr=True)
            t_full = min(t_full, time.perf_counter() - t0)

        # donate=True consumes its base, so each rep gets a fresh one;
        # rep 1 carries the scatter jit compile, min() discards it.
        t_delta = np.inf
        got = None
        for i in range(3):
            b = base if i == 2 else G.from_edges(s, r, N, source_csr=True)
            t0 = time.perf_counter()
            got = b.apply_delta(delta, donate=True)
            t_delta = min(t_delta, time.perf_counter() - t0)

        assert_graphs_bit_identical(got, want, "ratchet")
        ratio = t_full / t_delta
        assert ratio >= 10.0, (
            f"delta apply must be >=10x faster than the from-scratch "
            f"rebuild: rebuild {t_full * 1000:.0f} ms vs delta "
            f"{t_delta * 1000:.0f} ms = {ratio:.1f}x")
