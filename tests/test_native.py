"""Native graphcore kernels vs their numpy fallbacks — identical results,
and graph construction must be identical whichever path built it."""

import numpy as np
import pytest

from p2pnetwork_tpu import native


@pytest.fixture(autouse=True)
def restore_fallback():
    yield
    native.force_fallback(False)


def test_native_library_compiles_and_loads():
    assert native.available(), "g++ is in this image; the library must build"


class TestSortPairs:
    @pytest.mark.parametrize("n", [0, 1, 7, 1000, 100_000])
    def test_matches_numpy_stable_argsort(self, n):
        rng = np.random.default_rng(n)
        keys = rng.integers(0, max(n, 1), size=n, dtype=np.int32)
        vals = np.arange(n, dtype=np.int32)
        out_k, out_v = native.sort_pairs(keys, vals)
        order = np.argsort(keys, kind="stable")
        np.testing.assert_array_equal(out_k, keys[order])
        np.testing.assert_array_equal(out_v, vals[order])

    def test_stability_on_duplicate_keys(self):
        keys = np.zeros(1000, dtype=np.int32)
        vals = np.arange(1000, dtype=np.int32)
        _, out_v = native.sort_pairs(keys, vals)
        np.testing.assert_array_equal(out_v, vals)  # stable = order preserved

    def test_large_key_range_multi_pass(self):
        # Keys above 2^16 force the second radix pass; above 2^31-ish the
        # sign bit would break it, so int32 max range is the contract edge.
        rng = np.random.default_rng(0)
        keys = rng.integers(0, 2**31 - 1, size=50_000, dtype=np.int32)
        vals = np.arange(50_000, dtype=np.int32)
        out_k, out_v = native.sort_pairs(keys, vals)
        order = np.argsort(keys, kind="stable")
        np.testing.assert_array_equal(out_k, keys[order])
        np.testing.assert_array_equal(out_v, vals[order])


class TestSortUnique:
    @pytest.mark.parametrize("n", [0, 1, 1000, 200_000])
    def test_matches_numpy_unique(self, n):
        rng = np.random.default_rng(n)
        keys = rng.integers(0, max(n // 2, 1), size=n, dtype=np.int64)
        np.testing.assert_array_equal(native.sort_unique(keys), np.unique(keys))

    def test_large_values_multi_pass(self):
        rng = np.random.default_rng(1)
        keys = rng.integers(0, 10**12, size=100_000, dtype=np.int64)
        np.testing.assert_array_equal(native.sort_unique(keys), np.unique(keys))

    def test_input_not_mutated(self):
        keys = np.array([5, 3, 3, 1], dtype=np.int64)
        native.sort_unique(keys)
        np.testing.assert_array_equal(keys, [5, 3, 3, 1])

    def test_keys_above_2_48_terminate(self):
        # Regression: pair keys reach ~n^2; for n >= 2^24 that exceeds 2^48,
        # where the pass-count loop used to shift by >= 64 bits — undefined
        # behavior that spins forever on x86.
        rng = np.random.default_rng(2)
        keys = rng.integers(2**48, 2**62, size=50_000, dtype=np.int64)
        np.testing.assert_array_equal(native.sort_unique(keys), np.unique(keys))


def test_graph_identical_native_vs_fallback():
    from p2pnetwork_tpu.sim import graph as G

    def build():
        g = G.watts_strogatz(500, 6, 0.2, seed=3, blocked=True, hybrid=True)
        return g

    native.force_fallback(False)
    g_native = build()
    native.force_fallback(True)
    g_numpy = build()

    for field in ("senders", "receivers", "edge_mask", "node_mask",
                  "in_degree", "out_degree", "neighbors", "neighbor_mask"):
        np.testing.assert_array_equal(
            np.asarray(getattr(g_native, field)),
            np.asarray(getattr(g_numpy, field)),
            err_msg=field,
        )
    np.testing.assert_array_equal(
        np.asarray(g_native.blocked.src), np.asarray(g_numpy.blocked.src)
    )
    assert g_native.hybrid.offsets == g_numpy.hybrid.offsets


def test_from_edges_inline_reps_match_posthoc():
    from p2pnetwork_tpu.sim import graph as G

    g_inline = G.watts_strogatz(400, 4, 0.3, seed=1, blocked=True, hybrid=True)
    g_posthoc = G.watts_strogatz(400, 4, 0.3, seed=1).with_blocked().with_hybrid()
    np.testing.assert_array_equal(
        np.asarray(g_inline.blocked.src), np.asarray(g_posthoc.blocked.src)
    )
    np.testing.assert_array_equal(
        np.asarray(g_inline.blocked.local_dst),
        np.asarray(g_posthoc.blocked.local_dst),
    )
    assert g_inline.hybrid.offsets == g_posthoc.hybrid.offsets
    np.testing.assert_array_equal(
        np.asarray(g_inline.hybrid.masks), np.asarray(g_posthoc.hybrid.masks)
    )
