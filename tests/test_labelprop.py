"""LabelPropagation community detection: planted-community recovery,
mode correctness vs a numpy oracle, oscillation-freedom, and liveness
masking."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import LabelPropagation  # noqa: E402
from p2pnetwork_tpu.models.labelprop import _SENTINEL, _row_mode  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _two_cliques(half=8, bridges=1):
    """Two cliques of size ``half`` joined by ``bridges`` edges."""
    edges = []
    for base in (0, half):
        for i in range(half):
            for j in range(i + 1, half):
                edges.append((base + i, base + j))
    for b in range(bridges):
        edges.append((b, half + b))
    s = np.array([e[0] for e in edges], dtype=np.int32)
    r = np.array([e[1] for e in edges], dtype=np.int32)
    return G.from_edges(np.concatenate([s, r]), np.concatenate([r, s]),
                        2 * half)


def _run(g, max_rounds=128):
    p = LabelPropagation()
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="unsettled", threshold=1,
        max_rounds=max_rounds)
    return p, st, out


class TestRowMode:
    def test_mode_with_padding(self):
        big = int(_SENTINEL)
        row = jnp.sort(jnp.array([5, 3, 3, 9, big, big], dtype=jnp.int32))
        assert int(_row_mode(row)) == 3

    def test_tie_breaks_low(self):
        row = jnp.sort(jnp.array([7, 2, 7, 2, 1], dtype=jnp.int32))
        assert int(_row_mode(row)) == 2

    def test_all_padding(self):
        row = jnp.full(4, _SENTINEL, dtype=jnp.int32)
        assert int(_row_mode(row)) == int(_SENTINEL)


class TestLabelPropagation:
    def test_planted_two_communities(self):
        g = _two_cliques(half=8, bridges=1)
        p, st, out = _run(g)
        lab = np.asarray(st.label)
        # Each clique agrees internally; the two sides differ.
        assert len(np.unique(lab[:8])) == 1
        assert len(np.unique(lab[8:16])) == 1
        assert lab[0] != lab[8]
        assert int(p.communities(g, st)) == 2

    def test_no_oscillation_on_bipartite(self):
        # A 4-cycle is the canonical synchronous-LPA oscillator; the
        # parity schedule must settle it.
        s = np.array([0, 1, 2, 3, 1, 2, 3, 0], dtype=np.int32)
        r = np.array([1, 2, 3, 0, 0, 1, 2, 3], dtype=np.int32)
        g = G.from_edges(s, r, 4)
        p, st, out = _run(g, max_rounds=64)
        assert int(out["rounds"]) < 64, "never settled"

    def test_dense_graph_one_community(self):
        g = G.complete(12)
        p, st, _ = _run(g)
        lab = np.asarray(st.label)[:12]
        assert len(np.unique(lab)) == 1

    def test_dead_nodes_hold_minus_one(self):
        g = _two_cliques(half=6)
        g = failures.fail_nodes(g, np.array([2, 9]))
        p, st, _ = _run(g)
        lab = np.asarray(st.label)
        assert lab[2] == -1 and lab[9] == -1
        alive = np.asarray(g.node_mask)
        assert (lab[alive] >= 0).all()

    def test_deterministic(self):
        g = G.watts_strogatz(64, 6, 0.1, seed=3)
        _, st1, _ = _run(g)
        _, st2, _ = _run(g)
        assert (np.asarray(st1.label) == np.asarray(st2.label)).all()

    def test_first_round_never_reads_settled(self):
        # Regression: a 2-node path whose even half is stable at init.
        # With changed_prev seeded to 0, round 1 reported unsettled == 0
        # and the loop stopped before node 1 ever took its turn.
        s = np.array([0, 1], dtype=np.int32)
        r = np.array([1, 0], dtype=np.int32)
        g = G.from_edges(s, r, 2)
        p, st, out = _run(g)
        lab = np.asarray(st.label)
        assert lab[0] == lab[1] == 0, f"premature convergence: {lab[:2]}"
        assert int(out["rounds"]) >= 2

    def test_requires_neighbor_table(self):
        g = G.watts_strogatz(32, 4, 0.1, seed=1,
                             build_neighbor_table=False)
        with pytest.raises(ValueError):
            LabelPropagation().init(g, jax.random.key(0))

    def test_auto_path_parity(self):
        # Integer labels: GSPMD auto parity is exact (the vmapped
        # sorted-row mode partitions over the node axis).
        from tests.helpers import run_auto_parity

        st_a, st_r = run_auto_parity(
            G.watts_strogatz(256, 4, 0.2, seed=1), LabelPropagation(), 16)
        assert (np.asarray(st_a.label) == np.asarray(st_r.label)).all()
