"""DistanceVector (weighted Bellman-Ford + next hops) vs numpy oracles,
and the edge-weight plumbing (from_edges / with_weights / consolidate)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import DistanceVector, HopDistance  # noqa: E402
from p2pnetwork_tpu.ops import propagate_min_plus  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _live_weighted_edges(g):
    s, r = np.asarray(g.senders), np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    w = (np.asarray(g.edge_weight) if g.edge_weight is not None
         else np.ones(s.shape, np.float32))
    out = [(s[em], r[em], w[em])]
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        out.append((np.asarray(g.dyn_senders)[dm],
                    np.asarray(g.dyn_receivers)[dm],
                    np.ones(int(dm.sum()), np.float32)))
    return out


def _oracle_sssp(g, source):
    """Bellman-Ford fixpoint over the live weighted edges (numpy)."""
    n_pad = g.n_nodes_padded
    alive = np.asarray(g.node_mask)
    dist = np.full(n_pad, np.inf, dtype=np.float32)
    if alive[source]:
        dist[source] = 0.0
    for _ in range(n_pad):
        before = dist.copy()
        for s, r, w in _live_weighted_edges(g):
            cand = dist[s] + w
            np.minimum.at(dist, r, cand.astype(np.float32))
        dist[~alive] = np.inf
        if (dist == before).all():
            break
    return dist


def _converge(g, source=0, method="auto"):
    p = DistanceVector(source=source, method=method)
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="changed", threshold=1, max_rounds=1024)
    return p, st, out


def _ws_weighted(n=96, seed=7, **kw):
    g = G.watts_strogatz(n, 4, 0.2, seed=seed, **kw)
    # Deterministic pseudo-random positive costs from the edge endpoints.
    return g.with_weights(
        lambda s, r: 0.25 + ((s * 7919 + r * 104729) % 97) / 50.0)


class TestDistanceVector:
    def test_unweighted_equals_hopdistance(self):
        g = G.watts_strogatz(128, 4, 0.2, seed=1)
        _, st, _ = _converge(g)
        hst, _ = engine.run_until_coverage(
            g, HopDistance(source=0), jax.random.key(0),
            coverage_target=1.0, max_rounds=256)
        hops = np.asarray(hst.dist).astype(np.float32)
        want = np.where(hops < 0, np.inf, hops)
        np.testing.assert_array_equal(np.asarray(st.dist), want)

    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_weighted_matches_oracle(self, method):
        g = _ws_weighted()
        _, st, _ = _converge(g, method=method)
        np.testing.assert_allclose(np.asarray(st.dist), _oracle_sssp(g, 0),
                                   rtol=1e-6)

    def test_parents_are_optimal_and_next_hops_canonical(self):
        g = _ws_weighted(seed=8)
        p, st, _ = _converge(g)
        dist = np.asarray(st.dist)
        parent = np.asarray(st.parent)
        hops = np.asarray(p.next_hops(g, st))
        wmap = {}
        for s, r, w in _live_weighted_edges(g):
            for a, b, c in zip(s, r, w):
                wmap.setdefault(int(b), []).append((int(a), float(c)))
        for v in range(g.n_nodes):
            if v == 0 or not np.isfinite(dist[v]):
                assert parent[v] == -1 and hops[v] == -1
                continue
            best = min(dist[a] + c for a, c in wmap[v])
            assert dist[v] == pytest.approx(best, rel=1e-6)
            achievers = [a for a, c in wmap[v]
                         if np.float32(dist[a] + np.float32(c)) == dist[v]]
            # state.parent promises AN optimal predecessor (round-scoped
            # tie-break); next_hops promises the canonical lowest id.
            assert parent[v] in achievers
            assert hops[v] == min(achievers)

    def test_parent_and_next_hops_are_deterministic(self):
        g = _ws_weighted(seed=12)
        p, st1, _ = _converge(g)
        p2, st2, _ = _converge(g)
        np.testing.assert_array_equal(np.asarray(st1.parent),
                                      np.asarray(st2.parent))
        np.testing.assert_array_equal(np.asarray(p.next_hops(g, st1)),
                                      np.asarray(p2.next_hops(g, st2)))

    def test_failures_reroute(self):
        g = _ws_weighted(seed=9)
        gf = failures.fail_nodes(g, [3, 40, 77])
        _, st, _ = _converge(gf)
        np.testing.assert_allclose(np.asarray(st.dist), _oracle_sssp(gf, 0),
                                   rtol=1e-6)

    def test_dynamic_link_shortens_routes(self):
        # A long path graph; a runtime shortcut from 0 to the far end.
        n = 64
        base = np.arange(n - 1, dtype=np.int32)
        g = G.from_edges(*G._undirect(base, base + 1), n)
        g = topology.with_capacity(g, extra_edges=4)
        g2 = topology.connect(g, [0], [n - 1])
        _, st, _ = _converge(g2)
        np.testing.assert_allclose(np.asarray(st.dist), _oracle_sssp(g2, 0),
                                   rtol=1e-6)
        assert float(st.dist[n - 1]) == 1.0  # the unit-cost dynamic hop

    def test_dead_source_reaches_nothing(self):
        g = failures.fail_nodes(G.ring(16), [5])
        _, st, out = _converge(g, source=5)
        assert not np.isfinite(np.asarray(st.dist)).any()
        assert int(out["rounds"]) <= 1

    def test_auto_sharded_matches_engine(self):
        from p2pnetwork_tpu.parallel import auto, mesh as M

        g = _ws_weighted(n=512, seed=10)
        gs = auto.shard_graph_auto(g, M.ring_mesh(8))
        p = DistanceVector(source=0, method="segment")
        st, _ = auto.run_auto(gs, p, jax.random.key(0), 6)
        ref, _ = engine.run(g, p, jax.random.key(0), 6)
        np.testing.assert_allclose(np.asarray(st.dist), np.asarray(ref.dist),
                                   rtol=1e-6)


class TestWeightPlumbing:
    def test_from_edges_weights_survive_sort(self):
        s = np.array([2, 0, 1], dtype=np.int32)
        r = np.array([0, 1, 2], dtype=np.int32)
        w = np.array([5.0, 7.0, 9.0], dtype=np.float32)
        g = G.from_edges(s, r, 3, weights=w)
        hs = np.asarray(g.senders)[np.asarray(g.edge_mask)]
        hw = np.asarray(g.edge_weight)[np.asarray(g.edge_mask)]
        want = {2: 5.0, 0: 7.0, 1: 9.0}
        assert {int(a): float(b) for a, b in zip(hs, hw)} == want

    def test_neighbor_weight_aligned(self):
        g = _ws_weighted(n=64, seed=3)
        # Gather and segment lowerings agree => the [N, d] view is aligned.
        dist = jnp.where(jnp.arange(g.n_nodes_padded) == 0, 0.0, jnp.inf)
        a = propagate_min_plus(g, dist, "segment")
        b = propagate_min_plus(g, dist, "gather")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_with_weights_needs_alignment(self):
        g = G.ring(8)
        with pytest.raises(ValueError, match="align"):
            g.with_weights(np.ones(3, np.float32))

    def test_capped_table_rejected_post_hoc(self):
        g = G.watts_strogatz(64, 6, 0.1, seed=0, max_degree=2)
        with pytest.raises(ValueError, match="width-capped"):
            g.with_weights(lambda s, r: s + r + 1.0)

    def test_consolidate_preserves_weights_and_routes(self):
        g = _ws_weighted(seed=11)
        g = topology.with_capacity(g, extra_edges=8)
        g2 = topology.connect(g, [0, 7], [33, 61])
        _, st_before, _ = _converge(g2)
        g3 = topology.consolidate(g2)
        _, st_after, _ = _converge(g3)
        n = g2.n_nodes
        np.testing.assert_allclose(np.asarray(st_before.dist)[:n],
                                   np.asarray(st_after.dist)[:n], rtol=1e-6)
