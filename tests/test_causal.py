"""Causal broadcast (Birman-Schiper-Stephenson) delivery machine + live
ordering.

The delivery state machine is driven directly with adversarial arrival
orders (the races real networks produce, made deterministic), then a
live three-node integration confirms end-to-end causal order: every
node's delivery sequence must respect per-sender order and the
happened-before edges the vector clocks encode.
"""

from p2pnetwork_tpu import CausalNode
from p2pnetwork_tpu.causal import VC_FROM_KEY, VC_KEY
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


class Recorder(CausalNode):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.delivered = []
        self.delivered_clocks = []

    def causal_message(self, node, data):
        self.delivered.append(data)
        self.delivered_clocks.append(dict(self.vc))


class _FakeConn:
    def __init__(self, id):
        self.id = id


def _env(sender, clock, payload):
    return {VC_KEY: clock, VC_FROM_KEY: sender, "payload": payload}


class TestDeliveryMachine:
    """node_message driven directly — no sockets, no loop, pure ordering."""

    def _node(self):
        return Recorder(HOST, 0, id="me")

    def test_out_of_order_chain_buffers_then_releases(self):
        n = self._node()
        ca, cb = _FakeConn("A"), _FakeConn("B")
        # B's m2 causally follows A's m1 (B had delivered m1 before
        # sending), but m2 arrives FIRST.
        n.node_message(cb, _env("B", {"A": 1, "B": 1}, "m2"))
        assert n.delivered == [] and n.undelivered() == 1
        n.node_message(ca, _env("A", {"A": 1}, "m1"))
        assert n.delivered == ["m1", "m2"]
        assert n.undelivered() == 0

    def test_per_sender_gap_blocks(self):
        n = self._node()
        ca = _FakeConn("A")
        n.node_message(ca, _env("A", {"A": 2}, "second"))
        assert n.delivered == []
        n.node_message(ca, _env("A", {"A": 1}, "first"))
        assert n.delivered == ["first", "second"]

    def test_one_arrival_releases_whole_chain(self):
        n = self._node()
        ca, cb, cc = _FakeConn("A"), _FakeConn("B"), _FakeConn("C")
        n.node_message(cc, _env("C", {"A": 1, "B": 1, "C": 1}, "m3"))
        n.node_message(cb, _env("B", {"A": 1, "B": 1}, "m2"))
        assert n.delivered == [] and n.undelivered() == 2
        n.node_message(ca, _env("A", {"A": 1}, "m1"))
        assert n.delivered == ["m1", "m2", "m3"]

    def test_stale_duplicate_dropped(self):
        n = self._node()
        ca = _FakeConn("A")
        n.node_message(ca, _env("A", {"A": 1}, "m1"))
        n.node_message(ca, _env("A", {"A": 1}, "m1-again"))
        assert n.delivered == ["m1"]

    def test_duplicate_of_held_message_purged_on_release(self):
        # Regression: a resent copy buffered WHILE the original was held
        # used to survive delivery of the original and sit in _held
        # forever, inflating undelivered().
        n = self._node()
        ca = _FakeConn("A")
        n.node_message(ca, _env("A", {"A": 2}, "second"))
        n.node_message(ca, _env("A", {"A": 2}, "second-resent"))
        assert n.undelivered() == 2
        n.node_message(ca, _env("A", {"A": 1}, "first"))
        assert n.delivered == ["first", "second"]
        assert n.undelivered() == 0

    def test_concurrent_senders_any_order(self):
        n = self._node()
        ca, cb = _FakeConn("A"), _FakeConn("B")
        # A:1 and B:1 are concurrent — both deliverable on arrival,
        # either order is causal.
        n.node_message(cb, _env("B", {"B": 1}, "b1"))
        n.node_message(ca, _env("A", {"A": 1}, "a1"))
        assert sorted(n.delivered) == ["a1", "b1"]

    def test_plain_messages_bypass(self):
        seen = []

        class Plain(Recorder):
            def node_message(self, node, data):
                if isinstance(data, dict) and VC_KEY in data \
                        and VC_FROM_KEY in data:
                    return super().node_message(node, data)
                seen.append(data)

        n = Plain(HOST, 0, id="me")
        n.node_message(_FakeConn("A"), {"just": "a dict"})
        n.node_message(_FakeConn("A"), _env("A", {"A": 1}, "stamped"))
        assert seen == [{"just": "a dict"}]
        assert n.delivered == ["stamped"]


class TestLiveCausalOrder:
    def test_three_nodes_reactive_chain(self):
        a = Recorder(HOST, 0, id="A")
        b = Recorder(HOST, 0, id="B")
        c = Recorder(HOST, 0, id="C")
        nodes = [a, b, c]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert b.connect_with_node(HOST, c.port)
            assert c.connect_with_node(HOST, a.port)
            assert wait_until(
                lambda: all(len(n.all_nodes) == 2 for n in nodes))

            # B reacts to every message from A — each reaction causally
            # follows the message it answers.
            reacted = []
            orig = b.causal_message.__func__

            def reacting(node, data):
                orig(b, node, data)
                if isinstance(data, str) and data.startswith("a-"):
                    reacted.append(data)
                    b.send_causal(f"b-re-{data}")

            b.causal_message = reacting

            rounds = 10
            for i in range(rounds):
                a.send_causal(f"a-{i}")

            assert wait_until(
                lambda: len(c.delivered) >= 2 * rounds, timeout=10.0), \
                f"C delivered only {len(c.delivered)}"

            for n in (a, c):
                seq = [d for d in n.delivered if isinstance(d, str)]
                a_msgs = [d for d in seq if d.startswith("a-")]
                assert a_msgs == [f"a-{i}" for i in range(rounds)], \
                    f"per-sender order broken at {n.id}: {a_msgs}"
                # Every reaction lands after the message it reacts to.
                for i in range(rounds):
                    re = f"b-re-a-{i}"
                    if re in seq:
                        assert seq.index(f"a-{i}") < seq.index(re), \
                            f"causality violated at {n.id}: {re} before a-{i}"
            assert all(n.undelivered() == 0 for n in nodes)
        finally:
            stop_all(nodes)
