"""PushSum / PageRank / HopDistance tests: numpy oracles + invariants.

Same philosophy as the rest of the suite (SURVEY.md section 4): the
reference's socket tests assert on counts after sleeps; here every run is a
pure function of (graph, key), so assertions are exact — conservation laws
hold to rounding, and independent numpy re-implementations must agree."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood, HopDistance, PageRank, PushSum  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _edges(g):
    """Active (sender, receiver) pairs of a Graph, as numpy arrays."""
    m = np.asarray(g.edge_mask)
    return np.asarray(g.senders)[m], np.asarray(g.receivers)[m]


class TestPushSum:
    def test_mass_conservation(self):
        g = G.barabasi_albert(300, 3, seed=0)
        proto = PushSum()
        key = jax.random.key(1)
        s0 = np.asarray(proto.init(g, key).s).sum()
        _, stats = engine.run(g, proto, key, 30)
        s_tot = np.asarray(stats["s_total"])
        w_tot = np.asarray(stats["w_total"])
        np.testing.assert_allclose(s_tot, s0, rtol=1e-4)
        np.testing.assert_allclose(w_tot, g.n_nodes, rtol=1e-5)

    def test_converges_to_true_mean(self):
        g = G.watts_strogatz(400, 6, 0.1, seed=2)
        proto = PushSum()
        key = jax.random.key(3)
        state0 = proto.init(g, key)
        true_mean = np.asarray(state0.s)[: g.n_nodes].mean()
        # Diffusive mixing: the estimate spread shrinks by the spectral gap
        # per round; this graph needs ~200 rounds to reach 1e-3 (verified
        # against the float64 oracle).
        state, stats = engine.run(g, proto, key, 250)
        est = np.asarray(proto.estimate(g, state))[: g.n_nodes]
        np.testing.assert_allclose(est, true_mean, atol=1e-3)
        assert np.asarray(stats["variance"])[-1] < 1e-6

    def test_matches_numpy_oracle(self):
        g = G.erdos_renyi(64, 0.1, seed=4)
        proto = PushSum()
        key = jax.random.key(5)
        state = proto.init(g, key)
        s = np.asarray(state.s)[: g.n_nodes].astype(np.float64)
        w = np.asarray(state.w)[: g.n_nodes].astype(np.float64)
        snd, rcv = _edges(g)
        out_deg = np.bincount(snd, minlength=g.n_nodes)
        for _ in range(10):
            share_s = s / (out_deg + 1.0)
            share_w = w / (out_deg + 1.0)
            s = share_s + np.bincount(rcv, share_s[snd], minlength=g.n_nodes)
            w = share_w + np.bincount(rcv, share_w[snd], minlength=g.n_nodes)
        got, _ = engine.run(g, proto, key, 10)
        np.testing.assert_allclose(np.asarray(got.s)[: g.n_nodes], s, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(got.w)[: g.n_nodes], w, rtol=1e-4)

    def test_sink_keeps_mass(self):
        # 1 -> 0: node 1 has an outgoing edge; node 0 is a sink (out_deg 0).
        g = G.from_edges([1], [0], 2)
        proto = PushSum()
        key = jax.random.key(6)
        state, _ = engine.run(g, proto, key, 5)
        s_tot0 = np.asarray(proto.init(g, key).s).sum()
        np.testing.assert_allclose(np.asarray(state.s).sum(), s_tot0, rtol=1e-5)

    def test_conservation_under_failures(self):
        g = failures.fail_nodes(G.watts_strogatz(200, 4, 0.1, seed=7), [3, 50])
        proto = PushSum()
        key = jax.random.key(8)
        s0 = np.asarray(proto.init(g, key).s).sum()
        _, stats = engine.run(g, proto, key, 20)
        np.testing.assert_allclose(np.asarray(stats["s_total"])[-1], s0,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(stats["w_total"])[-1], 198,
                                   rtol=1e-5)


class TestPageRank:
    def test_matches_numpy_power_iteration(self):
        g = G.barabasi_albert(128, 3, seed=0)
        proto = PageRank(damping=0.85)
        n = g.n_nodes
        snd, rcv = _edges(g)
        out_deg = np.bincount(snd, minlength=n)
        r = np.full(n, 1.0 / n)
        for _ in range(25):
            contrib = np.where(out_deg > 0, r / np.maximum(out_deg, 1), 0.0)
            pulled = np.bincount(rcv, contrib[snd], minlength=n)
            dangling = r[out_deg == 0].sum()
            r = (1 - 0.85) / n + 0.85 * (pulled + dangling / n)
        state, _ = engine.run(g, proto, jax.random.key(0), 25)
        np.testing.assert_allclose(np.asarray(state.ranks)[:n], r, atol=1e-5)

    def test_ranks_sum_to_one_and_converge(self):
        g = G.watts_strogatz(500, 6, 0.1, seed=1)
        _, stats = engine.run(g, PageRank(), jax.random.key(0), 40)
        np.testing.assert_allclose(np.asarray(stats["rank_total"]), 1.0,
                                   atol=1e-4)
        res = np.asarray(stats["residual"])
        assert res[-1] < 1e-5 and res[-1] < res[0]

    def test_uniform_on_ring(self):
        # Symmetric ring: every node is equivalent -> uniform ranks.
        g = G.ring(64)
        state, _ = engine.run(g, PageRank(), jax.random.key(0), 30)
        np.testing.assert_allclose(np.asarray(state.ranks)[:64], 1 / 64,
                                   atol=1e-6)

    def test_dead_nodes_hold_no_rank(self):
        g = failures.fail_nodes(G.barabasi_albert(100, 3, seed=2), [1, 7])
        state, stats = engine.run(g, PageRank(), jax.random.key(0), 20)
        ranks = np.asarray(state.ranks)
        assert ranks[1] == 0.0 and ranks[7] == 0.0
        np.testing.assert_allclose(np.asarray(stats["rank_total"])[-1], 1.0,
                                   atol=1e-4)


class TestHopDistance:
    def _bfs(self, g, source):
        from collections import deque

        snd, rcv = _edges(g)
        adj = [[] for _ in range(g.n_nodes)]
        for u, v in zip(snd, rcv):
            adj[int(u)].append(int(v))
        dist = [-1] * g.n_nodes
        dist[source] = 0
        q = deque([source])
        while q:
            u = q.popleft()
            for v in adj[u]:
                if dist[v] < 0:
                    dist[v] = dist[u] + 1
                    q.append(v)
        return np.array(dist)

    def test_matches_bfs_oracle(self):
        g = G.watts_strogatz(300, 4, 0.1, seed=0)
        state, _ = engine.run(g, HopDistance(source=5), jax.random.key(0), 40)
        np.testing.assert_array_equal(np.asarray(state.dist)[: g.n_nodes],
                                      self._bfs(g, 5))

    def test_unreachable_stay_minus_one(self):
        # Two components: {0,1} and {2,3}; 4 isolated.
        g = G.from_edges([0, 2], [1, 3], 5)
        state, _ = engine.run(g, HopDistance(source=0), jax.random.key(0), 10)
        dist = np.asarray(state.dist)
        assert dist[0] == 0 and dist[1] == 1
        assert dist[2] == -1 and dist[3] == -1 and dist[4] == -1

    def test_coverage_loop_and_flood_agreement(self):
        # The BFS wave IS the flood wave: identical rounds-to-coverage, and
        # max_dist equals the round count.
        g = G.watts_strogatz(1000, 6, 0.1, seed=1)
        _, out_h = engine.run_until_coverage(g, HopDistance(source=0),
                                             jax.random.key(0),
                                             coverage_target=0.99)
        _, out_f = engine.run_until_coverage(g, Flood(source=0),
                                             jax.random.key(0),
                                             coverage_target=0.99)
        assert out_h["rounds"] == out_f["rounds"]
        assert out_h["messages"] == out_f["messages"]

    def test_eccentricity_on_ring(self):
        g = G.ring(32)  # symmetric ring: eccentricity = 16
        state, stats = engine.run(g, HopDistance(source=0), jax.random.key(0), 20)
        assert np.asarray(state.dist)[:32].max() == 16
        assert np.asarray(stats["max_dist"])[-1] == 16


class TestRunUntilConverged:
    def test_pagerank_to_residual(self):
        g = G.barabasi_albert(500, 3, seed=0)
        state, out = engine.run_until_converged(
            g, PageRank(), jax.random.key(0), stat="residual",
            threshold=1e-6,
        )
        assert out["value"] < 1e-6
        assert 0 < out["rounds"] < 200
        # The loop stopped exactly when the fixed-rounds run would have.
        _, stats = engine.run(g, PageRank(), jax.random.key(0), out["rounds"])
        res = np.asarray(stats["residual"])
        assert res[-1] < 1e-6 and (res[:-1] >= 1e-6).all()
        np.testing.assert_allclose(out["value"], res[-1], rtol=1e-6)
        assert out["messages"] == int(np.asarray(stats["messages"]).sum())

    def test_pushsum_to_variance(self):
        g = G.watts_strogatz(512, 8, 0.1, seed=1)
        proto = PushSum()
        state, out = engine.run_until_converged(
            g, proto, jax.random.key(2), stat="variance", threshold=1e-9,
        )
        assert out["value"] < 1e-9
        est = np.asarray(proto.estimate(g, state))[: g.n_nodes]
        true_mean = np.asarray(proto.init(g, jax.random.key(2)).s)[
            : g.n_nodes].mean()
        np.testing.assert_allclose(est, true_mean, atol=1e-3)

    def test_max_rounds_cap(self):
        g = G.ring(128)
        _, out = engine.run_until_converged(
            g, PageRank(), jax.random.key(0), stat="residual",
            threshold=0.0, max_rounds=7,
        )
        assert out["rounds"] == 7

    def test_unknown_stat_is_a_clear_error(self):
        g = G.ring(128)
        with pytest.raises(ValueError, match="exposes stats"):
            engine.run_until_converged(g, PageRank(), jax.random.key(0),
                                       stat="residul", threshold=1e-6)

    def test_coverage_loop_rejects_statless_protocol(self):
        from p2pnetwork_tpu.models import Gossip

        g = G.barabasi_albert(128, 3, seed=0)
        with pytest.raises(ValueError, match="needs \\['coverage'\\]"):
            engine.run_until_coverage(g, Gossip(), jax.random.key(0))


class TestEccentricities:
    def _oracle_ecc(self, g, src):
        import collections
        adj = collections.defaultdict(list)
        s, r = np.asarray(g.senders), np.asarray(g.receivers)
        em = np.asarray(g.edge_mask)
        alive = np.asarray(g.node_mask)
        for a, b in zip(s[em], r[em]):
            adj[a].append(b)
        if not alive[src]:
            return -1, 0
        dist = {src: 0}
        q = collections.deque([src])
        while q:
            u = q.popleft()
            for v in adj[u]:
                if alive[v] and v not in dist:
                    dist[v] = dist[u] + 1
                    q.append(v)
        return max(dist.values()), len(dist)

    def test_ring_eccentricities(self):
        from p2pnetwork_tpu.models import eccentricities
        g = G.ring(12)
        ecc, reached = eccentricities(g, np.arange(12))
        np.testing.assert_array_equal(np.asarray(ecc), np.full(12, 6))
        np.testing.assert_array_equal(np.asarray(reached), np.full(12, 12))

    def test_matches_bfs_oracle(self):
        from p2pnetwork_tpu.models import eccentricities
        g = G.watts_strogatz(128, 4, 0.2, seed=9)
        srcs = np.array([0, 5, 63, 127], dtype=np.int32)
        ecc, reached = eccentricities(g, srcs)
        for i, s in enumerate(srcs):
            want_ecc, want_reached = self._oracle_ecc(g, int(s))
            assert int(ecc[i]) == want_ecc
            assert int(reached[i]) == want_reached

    def test_dead_source(self):
        from p2pnetwork_tpu.models import eccentricities
        g = failures.fail_nodes(G.ring(8), [3])
        ecc, reached = eccentricities(g, np.array([3], dtype=np.int32))
        assert int(ecc[0]) == -1 and int(reached[0]) == 0

    def test_diameter_bounds_ring(self):
        from p2pnetwork_tpu.models import diameter_bounds
        g = G.ring(32)  # true diameter 16, every ecc = 16
        out = diameter_bounds(g, jax.random.key(0), samples=4)
        assert out["lower"] == 16 and out["upper"] == 32
        assert out["connected"]

    def test_diameter_bounds_bracket_truth(self):
        from p2pnetwork_tpu.models import diameter_bounds, eccentricities
        g = G.erdos_renyi(100, 0.06, seed=11)
        ecc_all, reached_all = eccentricities(
            g, np.arange(g.n_nodes_padded, dtype=np.int32))
        alive = np.asarray(g.node_mask)
        if bool((np.asarray(reached_all)[alive] == alive.sum()).all()):
            true_diam = int(np.asarray(ecc_all)[alive].max())
            out = diameter_bounds(g, jax.random.key(1), samples=8)
            assert out["lower"] <= true_diam <= out["upper"]
