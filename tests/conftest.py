"""Test configuration.

Tests run JAX on a virtual 8-device CPU platform so the sharded propagation
path (parallel/) is exercised on a real multi-device mesh without TPU
hardware. Benchmarks (bench.py) run outside pytest and keep the real TPU.

JAX_PLATFORMS is exported for any subprocesses tests may spawn, and applied
to this process through jax.config via utils.jax_env (an env var alone is
unreliable here — see that module's docstring). XLA_FLAGS is read at lazy
backend-client creation, which has not happened yet at conftest time, so the
host-platform device count takes effect.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Overwrite, not setdefault: this environment pre-sets JAX_PLATFORMS=axon
# (the tunneled TPU); tests are defined to run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import pytest  # noqa: E402


def pytest_configure(config):
    # Registered here (not only in pyproject) so ad-hoc invocations that
    # bypass pyproject's ini options stay warning-clean in tier-1:
    # `-m analysis` selects the graftlint static-analysis suite.
    config.addinivalue_line(
        "markers",
        "analysis: graftlint static-analysis + retrace_guard tests "
        "(select with -m analysis; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "supervise: supervised execution plane tests — watchdogs, "
        "checkpoint store, crash-tolerant runs (select with -m supervise; "
        "part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "audit: graftaudit IR-level audit tests — jaxpr rules, signature "
        "parity, donation aliasing, cost ratchet (select with -m audit; "
        "part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "buildperf: incremental-build perf ratchet — delta apply vs "
        "from-scratch rebuild ratio at 1M-edge scale (select with "
        "-m buildperf; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "race: graftrace deterministic-concurrency tests — scheduler "
        "replay, HB detector twins, scenario battery, CLI gate (select "
        "with -m race; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "batch: batched message plane tests — lane-packed kernels, "
        "MessageBatch lifecycle, batched-vs-sequential bit parity, the "
        "slow-marked 20x aggregate-throughput ratchet (select with "
        "-m batch; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "ring: comm-seam tests — ppermute vs Pallas ring-DMA halo "
        "backends bit-identical across the sharded protocol sweep and "
        "the lane-word batched path, plus the ICI byte accounting "
        "(select with -m ring; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "scope: graftscope observability tests — flight-recorder parity "
        "+ overhead ratchet, trace-plane span trees / Perfetto export, "
        "history ring + /history endpoint, probe_log and profiler "
        "wiring (select with -m scope; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "query: batched query-lane tests — byte-budgeted non-boolean "
        "carriers, min-plus/DHT/push-sum family identity sweeps, the "
        "query engine loop, and the slow-marked 10x aggregate ratchets "
        "(select with -m query; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "serve: graftserve serving front-end tests — submit/poll/stream "
        "lifecycle, admission pacing, quotas + structured load shedding, "
        "seeded-traffic determinism, preempt/resume bit-identity, the "
        "HTTP endpoints, and the slow-marked 1k-concurrent-lane soak "
        "(select with -m serve; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "quake: graftquake device-plane chaos tests — seeded halo-hop "
        "fault injection (byte-replayable, cross-backend bit-identical), "
        "dispatch chip-loss/wedge faults, integrity checks, RetryPolicy/"
        "Healer recovery bit-identity across engine/sharded/graftserve, "
        "and the slow-marked 100k chaos soak (select with -m quake; "
        "part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "sight: graftsight observability tests — ticket-scoped trace "
        "correlation (Perfetto-per-ticket under chaos), tick-phase "
        "profiler, SLO engine burn-rate alerts + AIMD consumption, "
        "/dashboard + query-param endpoints, tracer-on bit-identity, "
        "and the slow-marked serve-tick overhead ratchet (select with "
        "-m sight; part of the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "churn: graftchurn live-growth tests — bit-identical overlay "
        "growth with O(log K) repads, checkpoint/supervised resume "
        "across a repad, mid-service grow/delta mutations (zero lanes "
        "dropped, untouched tickets bit-identical), sidecar growth "
        "replay, seeded churn storms, and the slow-marked 100k "
        "churn-under-chaos soak (select with -m churn; part of the "
        "default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "mem: graftmem static memory plane tests — analytic liveness "
        "walk vs memory_analysis() parity, membudgets ratchet "
        "arithmetic, capacity-planner extrapolation, SimService "
        "hbm_budget_bytes admission gate (select with -m mem; part of "
        "the default tier-1 run)")
    config.addinivalue_line(
        "markers",
        "dur: graftdur durability tests — write-ahead intent journal "
        "(CRC records, torn-tail fuzz, segment rotation/compaction), "
        "crash-seam resume bit-identity, DurabilityLost shedding, "
        "hot-standby promote + FencedEpoch fencing, and the "
        "slow-marked crash-storm campaign + fsync overhead ratchet "
        "(select with -m dur; part of the default tier-1 run)")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Bound the live compiled-program count across the suite.

    The full suite (680+ tests, most jit-compiling several programs)
    accumulates every compiled executable in one process; past ~600
    tests the XLA CPU compiler has segfaulted inside LLVM on a program
    that compiles fine in isolation (reproduced twice at
    tests/test_walk.py, cleared by exactly this bounding). Cross-module
    cache hits are rare — modules compile their own protocols/shapes —
    so the recompile cost is noise.
    """
    yield
    if "jax" in sys.modules:  # sockets-only runs never import jax
        sys.modules["jax"].clear_caches()
