"""Test configuration.

Force JAX onto a virtual 8-device CPU platform *before* any test imports jax,
so the sharded propagation path (parallel/) is exercised on a real
multi-device mesh without TPU hardware. Benchmarks (bench.py) run outside
pytest and keep the real TPU backend.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
