"""HITS vs the networkx oracle on directed and undirected graphs, CSR /
scatter lowering agreement, dynamic links, and dead-node masking."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import networkx as nx  # noqa: E402

from p2pnetwork_tpu.models import HITS  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures, topology  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _nx_hits(g):
    s = np.asarray(g.senders)
    r = np.asarray(g.receivers)
    em = (np.asarray(g.edge_mask)
          & np.asarray(g.node_mask)[s] & np.asarray(g.node_mask)[r])
    H = nx.DiGraph()
    H.add_nodes_from(np.nonzero(np.asarray(g.node_mask))[0].tolist())
    H.add_edges_from(zip(s[em].tolist(), r[em].tolist()))
    if g.dyn_senders is not None:
        dm = np.asarray(g.dyn_mask)
        H.add_edges_from(zip(np.asarray(g.dyn_senders)[dm].tolist(),
                             np.asarray(g.dyn_receivers)[dm].tolist()))
    hubs, auths = nx.hits(H, max_iter=1000, tol=1e-12)
    h = np.zeros(g.n_nodes_padded)
    a = np.zeros(g.n_nodes_padded)
    for v, x in hubs.items():
        h[v] = x
    for v, x in auths.items():
        a[v] = x
    return h, a


def _run(g, rounds=200):
    p = HITS()
    st, out = engine.run_until_converged(
        g, p, jax.random.key(0), stat="residual", threshold=1e-6,
        max_rounds=rounds)
    return p, st, out


def _compare(g):
    p, st, _ = _run(g)
    h_nx, a_nx = _nx_hits(g)
    # networkx normalizes to sum=1; ours is L2 — compare shapes.
    for got, want in ((np.asarray(st.hub), h_nx),
                      (np.asarray(st.authority), a_nx)):
        gs, ws = got.sum(), want.sum()
        if ws > 0:
            np.testing.assert_allclose(got / max(gs, 1e-30),
                                       want / ws, atol=2e-4)


class TestHITS:
    def test_directed_star(self):
        # Dialers 1..5 all point at rendezvous node 0: node 0 is the
        # sole authority, the dialers are the hubs.
        s = np.arange(1, 6, dtype=np.int32)
        r = np.zeros(5, dtype=np.int32)
        g = G.from_edges(s, r, 6, build_neighbor_table=True)
        p, st, _ = _run(g)
        a = np.asarray(st.authority)
        h = np.asarray(st.hub)
        assert a[0] == pytest.approx(1.0, abs=1e-5)
        assert np.allclose(a[1:6], 0.0, atol=1e-6)
        assert np.allclose(h[1:6], h[1], atol=1e-6) and h[1] > 0.4
        assert h[0] == pytest.approx(0.0, abs=1e-6)

    @pytest.mark.parametrize("build", [
        lambda: G.watts_strogatz(64, 4, 0.2, seed=3),
        lambda: G.erdos_renyi(48, 0.1, seed=5),
    ])
    def test_matches_networkx(self, build):
        _compare(build())

    def test_directed_random_matches_networkx(self):
        rng = np.random.default_rng(0)
        s = rng.integers(0, 40, size=200).astype(np.int32)
        r = rng.integers(0, 40, size=200).astype(np.int32)
        keep = s != r
        # Dedup directed pairs: nx.DiGraph collapses multi-edges while
        # from_edges keeps every slot (and HITS would weight them).
        pairs = sorted(set(zip(s[keep].tolist(), r[keep].tolist())))
        s = np.array([p[0] for p in pairs], np.int32)
        r = np.array([p[1] for p in pairs], np.int32)
        g = G.from_edges(s, r, 40)
        _compare(g)

    def test_csr_and_scatter_lowerings_agree(self):
        g0 = G.watts_strogatz(96, 4, 0.2, seed=7)
        g1 = G.watts_strogatz(96, 4, 0.2, seed=7, source_csr=True)
        _, st0, _ = _run(g0)
        _, st1, _ = _run(g1)
        np.testing.assert_allclose(np.asarray(st0.hub),
                                   np.asarray(st1.hub), atol=1e-6)

    def test_auto_path_parity(self):
        # GSPMD auto-sharded run matches the engine (f32 tolerance: the
        # normalized sums reassociate under partitioning).
        from tests.helpers import run_auto_parity

        st_a, st_r = run_auto_parity(
            G.watts_strogatz(256, 4, 0.2, seed=1), HITS(method="segment"), 8)
        np.testing.assert_allclose(np.asarray(st_a.hub),
                                   np.asarray(st_r.hub), atol=1e-6)
        np.testing.assert_allclose(np.asarray(st_a.authority),
                                   np.asarray(st_r.authority), atol=1e-6)

    def test_csr_padding_sentinel_masked(self):
        # Regression: with the edge count an exact pad multiple, the
        # source-CSR padding slots all name edge e_pad-1 — a LIVE edge.
        # Unmasked, its contribution double-counts in the hub sum.
        g = G.watts_strogatz(96, 4, 0.2, seed=7)  # 384 = 3*128 edges
        assert g.n_edges == g.n_edges_padded
        gf = failures.fail_nodes(g, np.array([11]))
        _, st_plain, _ = _run(gf)
        _, st_csr, _ = _run(gf.with_source_csr())
        np.testing.assert_allclose(np.asarray(st_csr.hub),
                                   np.asarray(st_plain.hub), atol=1e-6)
        np.testing.assert_allclose(np.asarray(st_csr.authority),
                                   np.asarray(st_plain.authority),
                                   atol=1e-6)

    def test_dead_nodes_and_dynamic_links(self):
        g = G.watts_strogatz(48, 4, 0.2, seed=9)
        g = failures.fail_nodes(g, np.array([5, 17]))
        g = topology.with_capacity(g, extra_edges=4)
        g = topology.connect(g, [2, 30], [30, 2])
        _compare(g)
        p, st, _ = _run(g)
        assert np.asarray(st.hub)[[5, 17]].sum() == 0.0
        assert np.asarray(st.authority)[[5, 17]].sum() == 0.0
