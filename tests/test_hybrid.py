"""Hybrid (diagonal + blocked remainder) aggregation vs the segment
reference — exact OR equality and close sum agreement on graphs with full,
partial, and zero diagonal structure."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.ops import diag as D  # noqa: E402
from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


@pytest.fixture(params=["ws", "er", "ba", "ring"])
def graph(request):
    make = {
        # min_count floor is 128, so structured families here are built big
        # enough that their lattice diagonals actually get extracted.
        "ws": lambda: G.watts_strogatz(400, 6, 0.2, seed=0),
        "er": lambda: G.erdos_renyi(500, 0.02, seed=1),
        "ba": lambda: G.barabasi_albert(300, 4, seed=2),
        "ring": lambda: G.ring(257),
    }[request.param]
    return make().with_hybrid()


class TestHybridRepresentation:
    def test_partition_is_lossless(self, graph):
        """Diagonal edges + remainder edges == all edges, none counted twice."""
        h = graph.hybrid
        n_rem = (
            0 if h.remainder is None else int(np.asarray(h.remainder.mask).sum())
        )
        assert h.n_diag_edges + n_rem == graph.n_edges

    def test_diagonal_masks_match_edges(self, graph):
        """Every masked (offset, v) slot is a real edge (v+off)%n -> v."""
        h = graph.hybrid
        emask = np.asarray(graph.edge_mask)
        s = np.asarray(graph.senders)[emask]
        r = np.asarray(graph.receivers)[emask]
        edges = set(zip(s.tolist(), r.tolist()))
        masks = np.asarray(h.masks)
        for d, off in enumerate(h.offsets):
            for v in np.nonzero(masks[d])[0]:
                assert ((v + off) % h.n, v) in edges

    def test_ring_has_no_remainder(self):
        g = G.ring(257).with_hybrid()
        assert g.hybrid.remainder is None
        assert set(g.hybrid.offsets) == {1, 257 - 1}

    def test_er_has_no_diagonals(self):
        g = G.erdos_renyi(500, 0.02, seed=1).with_hybrid()
        assert g.hybrid.offsets == ()


class TestHybridEquality:
    def test_or_matches_segment(self, graph):
        key = jax.random.key(0)
        signal = jax.random.uniform(key, (graph.n_nodes_padded,)) < 0.15
        signal = signal & graph.node_mask
        ref = segment.propagate_or(graph, signal, "segment")
        out = segment.propagate_or(graph, signal, "hybrid")
        assert (np.asarray(out) == np.asarray(ref)).all()

    def test_sum_matches_segment(self, graph):
        key = jax.random.key(1)
        x = jax.random.normal(key, (graph.n_nodes_padded,), dtype=jnp.float32)
        x = x * graph.node_mask
        ref = np.asarray(segment.propagate_sum(graph, x, "segment"))
        out = np.asarray(segment.propagate_sum(graph, x, "hybrid"))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_flood_end_to_end(self, graph):
        ref_state, _ = engine.run(graph, Flood(source=0, method="segment"),
                                  jax.random.key(0), 5)
        state, _ = engine.run(graph, Flood(source=0, method="hybrid"),
                              jax.random.key(0), 5)
        assert (np.asarray(state.seen) == np.asarray(ref_state.seen)).all()


def test_hybrid_requires_representation():
    g = G.ring(200)
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool)
    with pytest.raises(ValueError, match="with_hybrid"):
        segment.propagate_or(g, sig, "hybrid")


def test_wraparound_offsets_padded_nodes():
    # n not a multiple of the 128 padding: the circular shift must wrap at n,
    # not at n_padded, or boundary nodes read padding slots.
    n = 300
    g = G.ring(n).with_hybrid()
    assert g.n_nodes_padded > n
    sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
    out = np.asarray(segment.propagate_or(g, sig, "hybrid"))
    expect = np.zeros(g.n_nodes_padded, dtype=bool)
    expect[[1, n - 1]] = True
    assert (out == expect).all()


def test_duplicate_edges_counted_exactly():
    # Regression: a mask slot can hold only one edge per (offset, receiver);
    # duplicate user-supplied edges must spill to the remainder, not vanish.
    n = 300
    base = np.arange(n, dtype=np.int32)
    s = np.concatenate([base, (base + 1) % n, [5, 5]])
    r = np.concatenate([(base + 1) % n, base, [6, 6]])
    g = G.from_edges(s, r, n).with_hybrid()
    ones = jnp.ones(g.n_nodes_padded, dtype=jnp.float32) * g.node_mask
    ref = np.asarray(segment.propagate_sum(g, ones, "segment"))
    out = np.asarray(segment.propagate_sum(g, ones, "hybrid"))
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    assert out[6] == 4.0  # ring both sides + two duplicates


def test_max_diags_cap_spills_to_remainder():
    g = G.watts_strogatz(400, 6, 0.0, seed=0)  # 6 pure lattice diagonals
    capped = g.with_hybrid(max_diags=2)
    assert len(capped.hybrid.offsets) == 2
    assert capped.hybrid.remainder is not None
    key = jax.random.key(0)
    sig = (jax.random.uniform(key, (g.n_nodes_padded,)) < 0.2) & g.node_mask
    full = g.with_hybrid()
    out_capped = segment.propagate_or(capped, sig, "hybrid")
    out_full = segment.propagate_or(full, sig, "hybrid")
    assert (np.asarray(out_capped) == np.asarray(out_full)).all()


def test_self_loops_do_not_displace_diagonals():
    # Regression (ADVICE r1, low): offset-0 (self-loop) filtering happened
    # AFTER the max_diags truncation, so frequent self-loops could evict a
    # qualifying real diagonal into the per-edge remainder.
    from p2pnetwork_tpu.ops.diag import build_hybrid_from_arrays

    n = 256
    base = np.arange(n, dtype=np.int32)
    # offset 0 on every node (count n), offset 1 (count n), offset 2 (n-1).
    s = np.concatenate([base, (base + 1) % n, ((base + 2) % n)[:-1]])
    r = np.concatenate([base, base, base[:-1]])
    order = np.argsort(r, kind="stable")
    h = build_hybrid_from_arrays(s[order], r[order], n, n,
                                 max_diags=2, min_count=16)
    assert sorted(h.offsets) == [1, 2]
