"""Graph construction + aggregation op tests (CPU, 8 virtual devices)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


class TestConstruction:
    def test_from_edges_sorted_and_padded(self):
        g = G.from_edges([0, 2, 1], [2, 1, 0], 3)
        assert g.n_nodes == 3 and g.n_edges == 3
        assert g.n_nodes_padded % 128 == 0
        assert g.n_edges_padded % 128 == 0
        r = np.asarray(g.receivers)[np.asarray(g.edge_mask)]
        assert (np.diff(r) >= 0).all()
        assert int(g.node_mask.sum()) == 3

    def test_degrees(self):
        g = G.from_edges([0, 0, 1], [1, 2, 2], 3)
        assert np.asarray(g.out_degree)[:3].tolist() == [2, 1, 0]
        assert np.asarray(g.in_degree)[:3].tolist() == [0, 1, 2]

    def test_neighbor_table_matches_coo(self):
        rng = np.random.default_rng(0)
        src = rng.integers(0, 50, 300).astype(np.int32)
        dst = rng.integers(0, 50, 300).astype(np.int32)
        keep = src != dst
        g = G.from_edges(src[keep], dst[keep], 50)
        # Every (sender, receiver) edge appears in the receiver's neighbor row.
        nb = np.asarray(g.neighbors)
        nbm = np.asarray(g.neighbor_mask)
        for s, d in zip(src[keep], dst[keep]):
            assert s in nb[d][nbm[d]]
        # Row lengths equal in-degrees.
        assert (nbm.sum(axis=1) == np.asarray(g.in_degree)).all()

    def test_edge_out_of_range_raises(self):
        with pytest.raises(ValueError):
            G.from_edges([0], [5], 3)

    def test_zero_edge_graph(self):
        g = G.from_edges(np.zeros(0), np.zeros(0), 10)
        assert g.n_edges == 0
        assert not np.asarray(g.neighbor_mask).any()
        # Propagation over an empty graph delivers nothing.
        sig = jnp.zeros(g.n_nodes_padded, bool).at[0].set(True)
        assert not np.asarray(segment.propagate_or(g, sig)).any()

    def test_erdos_renyi_zero_p(self):
        g = G.erdos_renyi(50, 0.0, seed=0)
        assert g.n_edges == 0


class TestGenerators:
    def test_ring(self):
        g = G.ring(10)
        assert g.n_edges == 20  # both directions
        assert (np.asarray(g.in_degree)[:10] == 2).all()

    def test_erdos_renyi_density(self):
        g = G.erdos_renyi(500, 0.02, seed=1)
        avg_deg = g.n_edges / 500
        assert 6 < avg_deg < 14  # expect ~= 2 * n*p = 20 endpoints -> 10 avg degree

    def test_erdos_renyi_degree_unbiased_across_index(self):
        # Regression: truncating sorted unique pair keys biased edges toward
        # low-index nodes (mean degree ~52 vs ~42 at n=500, p=0.1).
        degs_lo, degs_hi = [], []
        for seed in range(6):
            g = G.erdos_renyi(500, 0.1, seed=seed)
            deg = np.asarray(g.out_degree)[:500]
            degs_lo.append(deg[:100].mean())
            degs_hi.append(deg[400:].mean())
        lo, hi = np.mean(degs_lo), np.mean(degs_hi)
        assert abs(lo - hi) < 2.5, f"index-biased degrees: {lo:.1f} vs {hi:.1f}"

    def test_barabasi_albert_heavy_tail(self):
        g = G.barabasi_albert(400, 3, seed=2)
        deg = np.asarray(g.out_degree)[:400]
        assert deg.max() > 3 * np.median(deg)  # hubs exist

    def test_barabasi_albert_attachment_is_degree_proportional(self):
        # LCD correctness signal beyond "hubs exist": early nodes accumulate
        # far higher mean degree than late nodes, and mean degree ~= 2m.
        g = G.barabasi_albert(3000, 3, seed=0)
        deg = np.asarray(g.out_degree)[:3000]
        assert abs(deg.mean() - 6.0) < 0.7
        early, late = deg[:100].mean(), deg[2000:].mean()
        assert early > 3 * late, f"no preferential attachment: {early} vs {late}"

    def test_barabasi_albert_no_self_loops_or_duplicates(self):
        g = G.barabasi_albert(500, 4, seed=1)
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)[emask]
        r = np.asarray(g.receivers)[emask]
        assert (s != r).all()
        keys = s.astype(np.int64) * g.n_nodes_padded + r
        assert np.unique(keys).size == keys.size

    def test_watts_strogatz_degree(self):
        g = G.watts_strogatz(200, 4, 0.1, seed=3)
        deg = np.asarray(g.out_degree)[:200]
        # Each node originates k/2 ring edges in each direction pre-rewire.
        assert abs(deg.mean() - 4.0) < 0.5

    def test_generators_deterministic(self):
        a = G.watts_strogatz(100, 4, 0.3, seed=7)
        b = G.watts_strogatz(100, 4, 0.3, seed=7)
        assert (np.asarray(a.senders) == np.asarray(b.senders)).all()
        assert (np.asarray(a.receivers) == np.asarray(b.receivers)).all()


class TestAggregation:
    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_propagate_or_matches_bruteforce(self, method):
        rng = np.random.default_rng(4)
        src = rng.integers(0, 40, 200).astype(np.int32)
        dst = rng.integers(0, 40, 200).astype(np.int32)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        g = G.from_edges(src, dst, 40)
        signal = rng.random(g.n_nodes_padded) < 0.2
        signal &= np.asarray(g.node_mask)
        out = np.asarray(segment.propagate_or(g, jnp.asarray(signal), method))
        expected = np.zeros(g.n_nodes_padded, dtype=bool)
        for s, d in zip(src, dst):
            expected[d] |= signal[s]
        assert (out == expected).all()

    @pytest.mark.parametrize("method", ["segment", "gather"])
    def test_propagate_sum_matches_bruteforce(self, method):
        rng = np.random.default_rng(5)
        src = rng.integers(0, 30, 150).astype(np.int32)
        dst = rng.integers(0, 30, 150).astype(np.int32)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        g = G.from_edges(src, dst, 30)
        x = rng.standard_normal(g.n_nodes_padded).astype(np.float32)
        out = np.asarray(segment.propagate_sum(g, jnp.asarray(x), method))
        expected = np.zeros(g.n_nodes_padded, dtype=np.float32)
        for s, d in zip(src, dst):
            expected[d] += x[s]
        np.testing.assert_allclose(out[:30], expected[:30], rtol=1e-5)

    def test_frontier_messages(self):
        g = G.from_edges([0, 0, 1], [1, 2, 2], 3)
        frontier = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        assert int(segment.frontier_messages(g, frontier)) == 2


class TestCappedNeighborTable:
    """from_edges(max_degree=...) yields a sampled table — exact aggregation
    must not silently use it (regression: auto/gather used to drop edges)."""

    def _capped_hub(self):
        # 9 in-neighbors of node 0, table capped at width 4.
        src = np.arange(1, 10, dtype=np.int32)
        dst = np.zeros(9, dtype=np.int32)
        return G.from_edges(src, dst, 10, max_degree=4)

    def test_flag_set(self):
        assert not self._capped_hub().neighbors_complete
        assert G.ring(16).neighbors_complete

    def test_auto_falls_back_to_segment(self):
        g = self._capped_hub()
        signal = jnp.zeros(g.n_nodes_padded, dtype=bool).at[7].set(True)
        # Sender 7 is outside the capped table; auto must still deliver.
        out = np.asarray(segment.propagate_or(g, signal, "auto"))
        assert out[0]

    def test_explicit_gather_rejected(self):
        g = self._capped_hub()
        signal = jnp.zeros(g.n_nodes_padded, dtype=bool)
        with pytest.raises(ValueError, match="width-capped"):
            segment.propagate_or(g, signal, "gather")
        with pytest.raises(ValueError, match="width-capped"):
            segment.propagate_sum(g, signal.astype(jnp.float32), "gather")


class TestPaddingSortedness:
    """Padded receiver ids must keep the arrays non-decreasing — the
    indices_are_sorted=True promise of every segment reduction (regression:
    padding used to write zeros after the sorted active ids)."""

    def test_receivers_non_decreasing_including_padding(self):
        for g in [G.ring(10), G.watts_strogatz(100, 4, 0.3, seed=1),
                  G.erdos_renyi(90, 0.05, seed=2)]:
            r = np.asarray(g.receivers)
            assert (np.diff(r) >= 0).all(), "receivers not sorted with padding"

    def test_sharded_buckets_sorted_including_padding(self):
        from p2pnetwork_tpu.parallel import mesh as M
        from p2pnetwork_tpu.parallel import sharded

        g = G.watts_strogatz(256, 4, 0.2, seed=3)
        sg = sharded.shard_graph(g, M.ring_mesh(4))
        d = np.asarray(sg.bkt_dst)
        assert (np.diff(d, axis=-1) >= 0).all(), "bucket dsts not sorted"

    def test_watts_strogatz_no_duplicate_edges(self):
        g = G.watts_strogatz(500, 6, 0.5, seed=4)
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)[emask]
        r = np.asarray(g.receivers)[emask]
        keys = s.astype(np.int64) * 500 + r
        assert np.unique(keys).size == keys.size


class TestChord:
    def test_degree_and_diameter_are_logarithmic(self):
        from p2pnetwork_tpu.models import eccentricities
        n = 256
        g = G.chord(n)
        deg = np.asarray(g.in_degree)[:n]
        # Ring + fingers 2^1..2^7, both directions, dedup'd: ~2*log2(n).
        assert deg.max() <= 2 * n.bit_length()
        ecc, reached = eccentricities(g, np.array([0, 17, 255]))
        assert (np.asarray(reached) == n).all()
        assert int(np.asarray(ecc).max()) <= n.bit_length() - 1

    def test_non_power_of_two(self):
        g = G.chord(100)
        s = np.asarray(g.senders)[np.asarray(g.edge_mask)]
        r = np.asarray(g.receivers)[np.asarray(g.edge_mask)]
        assert ((s >= 0) & (s < 100) & (r >= 0) & (r < 100)).all()
        # Symmetric edge set (undirected).
        fwd = set(zip(s.tolist(), r.tolist()))
        assert all((b, a) in fwd for a, b in fwd)

    def test_config_build(self):
        from p2pnetwork_tpu.config import TopologyConfig
        g = G.build(TopologyConfig(kind="chord", n_nodes=64))
        assert g.n_nodes == 64


class TestKademlia:
    def test_power_of_two_is_hypercube(self):
        # k=1 on a fully-populated id space: partner per bucket is v^2^i —
        # exactly the binary hypercube.
        n = 64
        g = G.kademlia(n)
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)[emask]
        r = np.asarray(g.receivers)[emask]
        d = s ^ r
        assert (d == (d & -d)).all(), "non-power-of-two XOR distance at k=1"
        deg = np.asarray(g.in_degree)[:n]
        assert (deg == 6).all()  # log2(64) buckets, one partner each

    def test_bucket_coverage(self):
        # Every node has a partner in every bucket the id space populates.
        n, k = 100, 2
        g = G.kademlia(n, k)
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)[emask]
        r = np.asarray(g.receivers)[emask]
        d = s ^ r
        for v in (0, 1, 37, 99):
            mine = d[s == v]
            i = 0
            while (1 << i) < n:
                lo, hi = 1 << i, 1 << (i + 1)
                # The bucket is coverable iff some existing id lands in it.
                coverable = any(
                    lo <= (v ^ u) < hi for u in range(n) if u != v)
                got = ((mine >= lo) & (mine < hi)).any()
                assert got or not coverable, \
                    f"node {v} missing coverable bucket {i}"
                i += 1

    def test_diameter_logarithmic_and_symmetric(self):
        from p2pnetwork_tpu.models import eccentricities
        n = 200
        g = G.kademlia(n, k=1)
        ecc, reached = eccentricities(g, np.array([0, 3, 127, 199]))
        assert (np.asarray(reached) == n).all(), "kademlia graph disconnected"
        assert int(np.asarray(ecc).max()) <= 2 * n.bit_length()
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)[emask]
        r = np.asarray(g.receivers)[emask]
        fwd = set(zip(s.tolist(), r.tolist()))
        assert all((b, a) in fwd for a, b in fwd)

    def test_rejects_bad_params(self):
        import pytest as _pytest
        with _pytest.raises(ValueError):
            G.kademlia(1)
        with _pytest.raises(ValueError):
            G.kademlia(16, k=0)

    def test_config_build(self):
        from p2pnetwork_tpu.config import TopologyConfig
        g = G.build(TopologyConfig(kind="kademlia", n_nodes=64, k=2))
        assert g.n_nodes == 64
