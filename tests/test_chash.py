"""Consistent hashing: the two load-bearing properties (balance,
minimal disruption) plus replica sets, determinism, and edge cases."""

import numpy as np
import pytest

from p2pnetwork_tpu.utils import HashRing, hash_keys, moved_fraction


def _ring(n=10, vnodes=128):
    return HashRing([f"peer-{i}" for i in range(n)], vnodes=vnodes)


class TestBalance:
    def test_load_spreads_with_vnodes(self):
        r = _ring(10, vnodes=256)
        loads = list(r.load_fractions().values())
        assert sum(loads) == pytest.approx(1.0)
        # 10 peers -> 10% each; 256 vnodes keeps the spread tight-ish.
        assert max(loads) < 0.2 and min(loads) > 0.03

    def test_few_vnodes_skew_worse(self):
        tight = max(_ring(10, vnodes=512).load_fractions().values())
        loose = max(_ring(10, vnodes=1).load_fractions(seed=1).values())
        assert tight < loose


class TestDisruption:
    def test_single_join_moves_about_one_nth(self):
        r = _ring(10)
        r2 = r.add("peer-new")
        moved = moved_fraction(r, r2)
        # The newcomer takes ~1/11 of the space; nothing else moves.
        assert 0.02 < moved < 0.25
        # And the moved keys all moved TO the newcomer.
        rng = np.random.default_rng(3)
        pos = rng.integers(0, 2**64 - 1, size=4096, dtype=np.uint64)
        a, b = r.owners_at(pos), r2.owners_at(pos)
        assert all(y == "peer-new" for x, y in zip(a, b) if x != y)

    def test_single_leave_moves_only_its_slice(self):
        r = _ring(10)
        r2 = r.remove("peer-3")
        rng = np.random.default_rng(4)
        pos = rng.integers(0, 2**64 - 1, size=4096, dtype=np.uint64)
        a, b = r.owners_at(pos), r2.owners_at(pos)
        assert all(x == "peer-3" for x, y in zip(a, b) if x != y)

    def test_modulo_hashing_contrast(self):
        # The property modulo assignment lacks: adding one bucket to
        # hash % n reassigns ~all keys; the ring moves ~1/n.
        keys = [f"k{i}" for i in range(4096)]
        pos = hash_keys(keys)
        mod10 = pos % np.uint64(10)
        mod11 = pos % np.uint64(11)
        mod_moved = float(np.mean(mod10 != mod11))
        ring_moved = moved_fraction(_ring(10), _ring(10).add("peer-new"))
        assert mod_moved > 0.8
        assert ring_moved < 0.25


class TestLookups:
    def test_deterministic_across_instances(self):
        a, b = _ring(), _ring()
        for k in ("alpha", b"raw-bytes", 12345):
            assert a.owner(k) == b.owner(k)

    def test_replica_sets_distinct_and_stable(self):
        r = _ring(8)
        reps = r.owners("some-key", k=3)
        assert len(reps) == 3 and len(set(reps)) == 3
        assert reps[0] == r.owner("some-key")
        # k above the peer count: everyone, once.
        assert sorted(r.owners("some-key", k=99)) == sorted(r.node_ids)

    def test_zero_replicas_empty(self):
        r = _ring(6)
        assert r.owners("k", k=0) == []
        assert r.owners("k", k=-2) == []

    def test_empty_ring_raises(self):
        with pytest.raises(ValueError):
            HashRing().owner("x")
        with pytest.raises(ValueError):
            HashRing(["a"], vnodes=0)

    def test_add_remove_roundtrip(self):
        r = _ring(6)
        r2 = r.add("extra").remove("extra")
        assert r2.node_ids == r.node_ids
        assert moved_fraction(r, r2) == 0.0

    def test_duplicate_ids_collapse(self):
        r = HashRing(["a", "b", "a"], vnodes=16)
        assert r.node_ids == ("a", "b")
