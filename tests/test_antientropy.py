"""AntiEntropy push-pull reconciliation: monotonicity, locality, and
full replication."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import AntiEntropy, AntiEntropyState  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _neighbors_of(g, v):
    s, r = np.asarray(g.senders), np.asarray(g.receivers)
    em = np.asarray(g.edge_mask)
    return set(s[em & (r == v)]) | set(r[em & (s == v)])


class TestAntiEntropy:
    @pytest.mark.parametrize("push,pull", [(True, True), (True, False),
                                           (False, True)])
    def test_full_replication_on_connected_graph(self, push, pull):
        g = G.watts_strogatz(128, 4, 0.1, seed=0)
        p = AntiEntropy(n_items=16, push=push, pull=pull)
        st, out = engine.run_until_converged(
            g, p, jax.random.key(1), stat="missing", threshold=1,
            max_rounds=2048)
        assert int(out["value"]) == 0
        have = np.asarray(st.have)
        assert have[:128].all()

    def test_possession_is_monotone_and_local(self):
        g = G.watts_strogatz(64, 4, 0.2, seed=2)
        p = AntiEntropy(n_items=8)
        # Craft: item 0 held only by node 5.
        have = jnp.zeros((g.n_nodes_padded, 8), dtype=bool).at[5, 0].set(True)
        st = AntiEntropyState(have=have, round=jnp.int32(0))
        st2, _ = p.step(g, st, jax.random.key(3))
        before = np.asarray(st.have)
        after = np.asarray(st2.have)
        assert (after | before == after).all()  # monotone
        gained = np.flatnonzero(after[:, 0] & ~before[:, 0])
        allowed = _neighbors_of(g, 5)
        assert set(gained) <= allowed  # one hop per round, edges only

    def test_dead_nodes_neither_give_nor_take(self):
        g = failures.fail_nodes(G.ring(16), [4])
        p = AntiEntropy(n_items=4)
        st = p.init(g, jax.random.key(4))
        # The surviving graph is a 15-node path — epidemic spread there
        # is O(n) rounds with real variance, so give it slack.
        for i in range(160):
            st, out = p.step(g, st, jax.random.key(100 + i))
        have = np.asarray(st.have)
        assert not have[4].any()
        alive = np.asarray(g.node_mask)
        assert have[alive].all()  # the ring minus one node stays connected

    def test_requires_neighbor_table(self):
        g = G.ring(16, build_neighbor_table=False)
        with pytest.raises(ValueError, match="neighbor table"):
            AntiEntropy().init(g, jax.random.key(0))

    def test_push_pull_beats_pull_only(self):
        g = G.watts_strogatz(256, 4, 0.1, seed=5)
        rounds = {}
        for name, (push, pull) in {"both": (True, True),
                                   "pull": (False, True)}.items():
            p = AntiEntropy(n_items=32, push=push, pull=pull)
            _, out = engine.run_until_converged(
                g, p, jax.random.key(6), stat="missing", threshold=1,
                max_rounds=4096)
            assert int(out["value"]) == 0
            rounds[name] = int(out["rounds"])
        assert rounds["both"] <= rounds["pull"]
