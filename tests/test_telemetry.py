"""The unified telemetry plane: registry types, both exporters, the
Prometheus endpoint, and the sockets/sim/parallel instrumentation.

One registry across every backend is the subsystem's whole point, so the
tests here cross layers deliberately: real TCP nodes and compiled sim
runs both land in the same snapshot, the text exposition a scraper sees
is validated line-by-line, and the JSONL schema is pinned as the shared
envelope EventLog events and metric samples ride together.
"""

import io
import json
import math
import re
import threading
import time
import urllib.request

import pytest

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.utils import EventLog


@pytest.fixture
def reg():
    """A fresh registry swapped in as the process default, restored after —
    instrumentation sites resolve the default at call time, so every module
    under test reports here without plumbing."""
    fresh = telemetry.Registry()
    prev = telemetry.set_default_registry(fresh)
    yield fresh
    telemetry.set_default_registry(prev)


# --------------------------------------------------------------- registry


class TestRegistryTypes:
    def test_counter_monotone(self, reg):
        c = reg.counter("events_total", "help text")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_bidirectional(self, reg):
        g = reg.gauge("queue_depth")
        g.set(10)
        g.dec(3)
        g.inc()
        assert g.value == 8

    def test_histogram_exponential_buckets(self, reg):
        h = reg.histogram("lat_seconds",
                          buckets=telemetry.exponential_buckets(0.001, 10, 3))
        assert h.buckets == (0.001, 0.01, 0.1)
        for v in (0.0005, 0.005, 0.05, 5.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(5.0555)
        cum = h._anon().cumulative()
        assert cum == [(0.001, 1), (0.01, 2), (0.1, 3), (math.inf, 4)]

    def test_labels_create_independent_children(self, reg):
        c = reg.counter("sent_total", "", ("node", "peer"))
        c.labels("a", "b").inc(5)
        c.labels(node="a", peer="c").inc()
        assert reg.value("sent_total", node="a", peer="b") == 5
        assert reg.value("sent_total", node="a", peer="c") == 1
        assert reg.value("sent_total", node="x", peer="y") == 0

    def test_label_arity_and_names_enforced(self, reg):
        c = reg.counter("c_total", "", ("node",))
        with pytest.raises(ValueError):
            c.labels("a", "b")
        with pytest.raises(ValueError):
            c.labels(peer="a")
        with pytest.raises(ValueError):
            c.inc()  # labeled metric needs .labels() first

    def test_get_or_create_is_idempotent_but_type_safe(self, reg):
        c1 = reg.counter("x_total")
        assert reg.counter("x_total") is c1
        with pytest.raises(ValueError):
            reg.gauge("x_total")
        with pytest.raises(ValueError):
            reg.counter("x_total", labelnames=("other",))

    def test_invalid_metric_names_rejected(self, reg):
        for bad in ("", "has space", "has-dash", "1leading"):
            with pytest.raises(ValueError):
                reg.counter(bad)

    def test_thread_safety_under_contention(self, reg):
        c = reg.counter("hits_total")
        h = reg.histogram("obs", buckets=(1.0,))

        def work():
            for _ in range(2000):
                c.inc()
                h.observe(0.5)

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 16000
        assert h.count == 16000

    def test_snapshot_shape(self, reg):
        reg.counter("a_total", "ha").inc(2)
        reg.histogram("h", buckets=(1.0, 2.0)).observe(1.5)
        snap = reg.snapshot()
        assert snap["a_total"]["type"] == "counter"
        assert snap["a_total"]["samples"] == [{"labels": {}, "value": 2.0}]
        hsamp = snap["h"]["samples"][0]
        assert hsamp["count"] == 1 and hsamp["sum"] == 1.5
        assert hsamp["buckets"]["+Inf"] == 1

    def test_value_with_partial_or_unknown_labels_is_zero(self, reg):
        reg.counter("c_total", "", ("node", "peer")).labels("a", "b").inc()
        assert reg.value("c_total", node="a") == 0.0       # partial
        assert reg.value("c_total", nope="a") == 0.0       # unknown name
        assert reg.value("c_total") == 0.0                 # no labels
        assert reg.value("c_total", node="a", peer="b") == 1.0

    def test_remove_prunes_child(self, reg):
        g = reg.gauge("phi", "", ("peer",))
        g.labels("x").set(3)
        g.labels("y").set(4)
        g.remove("x")
        g.remove("never-existed")  # no-op
        assert reg.value("phi", peer="x") == 0.0
        assert reg.value("phi", peer="y") == 4.0
        assert len(g.children()) == 1
        with pytest.raises(ValueError):
            g.remove(wrong_name="x")

    def test_default_registry_swap(self):
        fresh = telemetry.Registry()
        prev = telemetry.set_default_registry(fresh)
        try:
            assert telemetry.default_registry() is fresh
        finally:
            telemetry.set_default_registry(prev)
        assert telemetry.default_registry() is prev


# --------------------------------------------------------------- exporters


#: One sample line of text exposition: name{labels} value
_SAMPLE_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$')


def _assert_valid_exposition(text):
    families = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            name, kind = line.split()[2:4]
            assert kind in ("counter", "gauge", "histogram")
            families.add(name)
        elif line.startswith("# HELP "):
            continue
        else:
            assert _SAMPLE_LINE.match(line), f"bad exposition line: {line!r}"
    return families


class TestExporters:
    def test_prometheus_text_exposition(self, reg):
        reg.counter("msgs_total", "messages", ("node",)).labels("a").inc(3)
        reg.gauge("depth").set(-2.5)
        reg.histogram("lat", "latency", buckets=(0.1, 1.0)).observe(0.5)
        text = telemetry.to_prometheus(reg)
        families = _assert_valid_exposition(text)
        assert families == {"msgs_total", "depth", "lat"}
        assert 'msgs_total{node="a"} 3\n' in text
        assert "depth -2.5" in text
        assert 'lat_bucket{le="0.1"} 0' in text
        assert 'lat_bucket{le="1"} 1' in text
        assert 'lat_bucket{le="+Inf"} 1' in text
        assert "lat_sum 0.5" in text
        assert "lat_count 1" in text

    def test_prometheus_label_escaping(self, reg):
        reg.counter("c_total", "", ("p",)).labels('we"ird\\pa\nth').inc()
        text = telemetry.to_prometheus(reg)
        assert r'p="we\"ird\\pa\nth"' in text

    def test_jsonl_stream_roundtrips(self, reg):
        reg.counter("c_total", "", ("k",)).labels("v").inc(7)
        reg.histogram("h", buckets=(1.0,)).observe(2.0)
        buf = io.StringIO()
        n = telemetry.write_jsonl(reg, buf)
        lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        assert n == len(lines) == 2
        counter = next(r for r in lines if r["type"] == "counter")
        assert counter["name"] == "c_total"
        assert counter["labels"] == {"k": "v"}
        assert counter["value"] == 7
        hist = next(r for r in lines if r["type"] == "histogram")
        assert hist["count"] == 1 and hist["sum"] == 2.0
        assert hist["buckets"]["+Inf"] == 1

    def test_jsonl_to_path_appends(self, reg, tmp_path):
        reg.counter("c_total").inc()
        path = tmp_path / "metrics.jsonl"
        telemetry.write_jsonl(reg, str(path))
        telemetry.write_jsonl(reg, str(path))
        assert len(path.read_text().splitlines()) == 2


# ------------------------------------------------------ eventlog schema fold


class TestEventLogJsonl:
    def test_round_trip_through_telemetry_schema(self):
        log = EventLog()
        log.record("node_message", "peer-1", {"k": 1})
        log.record("outbound_node_connected", "peer-2")
        log.record("inbound_node_connection_error", None,
                   {"exception": ValueError("boom")})
        buf = io.StringIO()
        assert log.to_jsonl(buf) == 3
        recs = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        originals = log.snapshot()
        for rec, orig in zip(recs, originals):
            assert rec["type"] == "event"
            assert rec["name"] == orig.event
            assert rec["ts"] == orig.timestamp
            if orig.peer_id is None:
                assert rec["labels"] == {}
            else:
                assert rec["labels"] == {"peer": orig.peer_id}
        assert recs[0]["data"] == {"k": 1}
        # non-JSON data (the exception) must ride as its repr, not crash
        assert "ValueError" in recs[2]["data"]["exception"] \
            if isinstance(recs[2]["data"], dict) else "ValueError" in recs[2]["data"]

    def test_clear_empties_history(self):
        log = EventLog()
        log.record("e")
        log.clear()
        assert log.count() == 0
        assert log.to_jsonl(io.StringIO()) == 0

    def test_events_and_metrics_share_one_stream(self, reg):
        reg.counter("c_total").inc()
        log = EventLog()
        log.record("node_message", "p")
        buf = io.StringIO()
        telemetry.write_jsonl(reg, buf)
        log.to_jsonl(buf)
        kinds = {json.loads(ln)["type"] for ln in buf.getvalue().splitlines()}
        assert kinds == {"counter", "event"}


# ------------------------------------------------------------ sockets plane


def _wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestSocketsInstrumentation:
    def test_node_traffic_lands_in_registry(self, reg):
        from p2pnetwork_tpu.node import Node

        a = Node("127.0.0.1", 0, id="ta")
        b = Node("127.0.0.1", 0, id="tb")
        try:
            a.start()
            b.start()
            assert a.telemetry is reg  # default registry resolved at init
            a.connect_with_node("127.0.0.1", b.port)
            assert _wait_until(lambda: len(b.nodes_inbound) == 1)
            a.send_to_nodes({"x": 1})
            b.send_to_nodes("pong")
            assert _wait_until(
                lambda: reg.value("p2p_messages_received_total", node="ta") >= 1
                and reg.value("p2p_messages_received_total", node="tb") >= 1)

            assert reg.value("p2p_messages_sent_total", node="ta") == \
                a.message_count_send == 1
            assert reg.value("p2p_bytes_sent_total", node="ta", peer="tb") > 0
            assert reg.value("p2p_bytes_received_total", node="tb", peer="ta") > 0
            # handle-latency histogram saw each delivered message
            h = reg.get("p2p_message_handle_seconds")
            assert h.labels("tb").count >= 1
            assert reg.value("p2p_connections", node="ta",
                             direction="outbound") == 1
            assert reg.value("p2p_connections", node="tb",
                             direction="inbound") == 1
            assert reg.value("p2p_events_total", node="ta",
                             event="outbound_node_connected") == 1
        finally:
            a.stop()
            b.stop()
            a.join(timeout=10)
            b.join(timeout=10)
        # teardown zeroes the gauges and counts disconnect events
        assert reg.value("p2p_connections", node="tb",
                         direction="inbound") == 0

    def test_recv_error_counter_mirrors_legacy_int(self, reg):
        from p2pnetwork_tpu.node import Node

        a = Node("127.0.0.1", 0, id="ea")
        b = Node("127.0.0.1", 0, id="eb")

        def crash(event, main, conn, data):
            if event == "node_message":
                raise RuntimeError("handler bug")

        b.callback = crash
        try:
            a.start()
            b.start()
            a.connect_with_node("127.0.0.1", b.port)
            assert _wait_until(lambda: len(b.nodes_inbound) == 1)
            a.send_to_nodes("boom")
            assert _wait_until(lambda: b.message_count_rerr >= 1)
            assert reg.value("p2p_recv_errors_total", node="eb") == \
                b.message_count_rerr
        finally:
            a.stop()
            b.stop()
            a.join(timeout=10)
            b.join(timeout=10)

    def test_phi_suspicion_gauge(self, reg):
        from p2pnetwork_tpu.phi import PhiAccrualNode

        n = PhiAccrualNode("127.0.0.1", 0, id="phi-node")
        try:
            # Feed the estimator directly (unit-level: no real peer needed).
            t0 = 100.0
            for i in range(10):
                n._record_heartbeat("peer-x", t0 + i * 1.0)
            phi = n.phi("peer-x", now=t0 + 9 + 30.0)  # long silence
            assert phi > 1.0
            assert reg.value("p2p_phi_suspicion", node="phi-node",
                             peer="peer-x") == pytest.approx(phi)
            assert reg.value("p2p_heartbeats_received_total",
                             node="phi-node") == 10
            # a departed peer's gauge sample is PRUNED, not zeroed — under
            # churn a forever-growing sample set would be the leak
            class _Gone:
                id = "peer-x"
            n.node_disconnected(_Gone())
            m = reg.get("p2p_phi_suspicion")
            assert all(c.labels != ("phi-node", "peer-x")
                       for c in m.children())
        finally:
            n.sock.close()


# ---------------------------------------------------------------- sim plane


jax = pytest.importorskip("jax")


class TestSimInstrumentation:
    def test_run_until_coverage_bridges_summary(self, reg):
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.sim import engine
        from p2pnetwork_tpu.sim import graph as G

        g = G.watts_strogatz(400, 4, 0.1, seed=0)
        state, out = engine.run_until_coverage(
            g, Flood(source=0), jax.random.key(0), coverage_target=0.99,
            max_rounds=64)
        assert reg.value("sim_runs_total", loop="coverage") == 1
        assert reg.value("sim_rounds_total", loop="coverage") == out["rounds"]
        assert reg.value("sim_messages_total",
                         loop="coverage") == out["messages"]
        assert reg.value("sim_last_coverage",
                         loop="coverage") == pytest.approx(out["coverage"])
        assert reg.value("sim_transfer_bytes_total") > 0
        h = reg.get("sim_run_seconds")
        assert h is not None and h.labels("coverage").count == 1
        assert h.labels("coverage").sum > 0

    def test_batch_loop_gauges_and_completion_histogram(self, reg):
        from p2pnetwork_tpu.models.messagebatch import BatchFlood
        from p2pnetwork_tpu.sim import engine
        from p2pnetwork_tpu.sim import graph as G

        g = G.watts_strogatz(300, 4, 0.1, seed=0, source_csr=True)
        proto = BatchFlood()
        batch = proto.init(g, [0, 5, 9])
        batch, out = engine.run_batch_until_coverage(
            g, proto, batch, jax.random.key(0), max_rounds=64,
            donate=False)
        assert reg.value("sim_runs_total", loop="batch") == 1
        assert reg.value("sim_rounds_total", loop="batch") == out["rounds"]
        assert reg.value("sim_messages_total",
                         loop="batch") == out["messages"]
        # per-batch occupancy gauge: all 3 lanes completed -> 0 running
        assert reg.value("sim_batch_active_lanes") == 0
        # one completion observation per lane that finished THIS call
        h = reg.get("sim_batch_completion_rounds")
        assert h is not None and h._anon().count == 3
        assert h._anon().sum == float(sum(out["lane_rounds"][:3]))
        # a resume of the finished batch must not re-observe those lanes
        engine.run_batch_until_coverage(
            g, proto, batch, jax.random.key(0), max_rounds=4,
            donate=False)
        assert reg.get("sim_batch_completion_rounds")._anon().count == 3
        # the batch loop also lands in the shared occupancy histogram
        occ = reg.get("sim_frontier_occupancy")
        assert occ is not None and occ.labels("batch", "BatchFlood").count

    def test_converged_loop_reports_without_coverage_gauge(self, reg):
        from p2pnetwork_tpu.models import LeaderElection
        from p2pnetwork_tpu.sim import engine
        from p2pnetwork_tpu.sim import graph as G

        g = G.watts_strogatz(200, 4, 0.1, seed=1)
        engine.run_until_converged(
            g, LeaderElection(), jax.random.key(2), stat="changed",
            threshold=1, max_rounds=64)
        assert reg.value("sim_runs_total", loop="converged") == 1
        # the converged loop's packed f32 slot is its stat, not a coverage
        assert reg.get("sim_last_coverage") is None or \
            not reg.get("sim_last_coverage")._children.get(("converged",))

    def test_injected_failures_counted(self, reg):
        from p2pnetwork_tpu.sim import failures
        from p2pnetwork_tpu.sim import graph as G

        g = G.watts_strogatz(100, 4, 0.0, seed=0)
        failures.fail_nodes(g, [1, 2, 3])
        failures.fail_edges(g, [0])
        failures.random_node_failures(g, jax.random.key(0), 0.1)
        assert reg.value("sim_injected_failures_total", kind="node") == 3
        assert reg.value("sim_injected_failures_total", kind="edge") == 1
        assert reg.value("sim_injected_failures_total", kind="node_draw") == 1

    def test_compile_hooks_count_backend_compiles(self, reg):
        from p2pnetwork_tpu.telemetry import jaxhooks

        if not jaxhooks.install():
            pytest.skip("jax.monitoring unavailable")
        before = jaxhooks.compile_count(reg)
        # a fresh lambda object is always a jit-cache miss -> compiles
        jax.jit(lambda x: x * 2 + 1)(jax.numpy.arange(7))
        assert jaxhooks.compile_count(reg) >= before + 1
        assert jaxhooks.compile_seconds(reg) > 0


# ----------------------------------------------------- parallel (commviz)


class TestCommvizRegistryBridge:
    # Synthetic HLO exercising the collective-permute branch — the
    # source_target_pairs form named a blind-spot risk in the module
    # docstring: permutes carry no replica_groups, so skipping them would
    # blind the DCN budget to cross-host permute traffic.
    HLO = "\n".join([
        "  %cp1 = f32[1024]{0} collective-permute(%x), "
        "source_target_pairs={{0,1},{2,3}}",                   # within-host
        "  %cp2 = f32[256]{0} collective-permute-start(%y), "
        "source_target_pairs={{1,2},{3,0}}",                   # cross-host
        "  %ar = f32[128]{0} all-reduce(%z), replica_groups={{0,1},{2,3}}",
    ])

    @staticmethod
    def _host_of(d):
        return d // 2  # devices 0,1 on host 0; 2,3 on host 1

    def test_permute_pairs_parsed(self):
        from p2pnetwork_tpu.parallel import commviz

        line = self.HLO.splitlines()[0]
        assert commviz.permute_pairs(line) == [(0, 1), (2, 3)]

    def test_classification_covers_permutes(self):
        from p2pnetwork_tpu.parallel import commviz

        within, cross = commviz.classify_collective_bytes(
            self.HLO, self._host_of)
        # cp1 (4096 B) and the all-reduce (512 B) stay on-host; the async
        # cp2 (1024 B) crosses hosts 0<->1.
        assert within == 4096 + 512
        assert cross == 1024

    def test_record_traffic_feeds_registry_gauges(self, reg):
        from p2pnetwork_tpu.parallel import commviz

        within, cross = commviz.record_traffic(
            self.HLO, self._host_of, program="ring_flood")
        assert (within, cross) == (4608, 1024)
        assert reg.value("comm_collective_bytes", program="ring_flood",
                         placement="within_host") == 4608
        assert reg.value("comm_collective_bytes", program="ring_flood",
                         placement="cross_host") == 1024
        # re-recording the same program overwrites (gauge), not accumulates
        commviz.record_traffic(self.HLO, self._host_of, program="ring_flood")
        assert reg.value("comm_collective_bytes", program="ring_flood",
                         placement="cross_host") == 1024


# ------------------------------------------------------------ the endpoint


class TestPrometheusEndpoint:
    def test_endpoint_serves_at_least_8_families(self, reg):
        from p2pnetwork_tpu.models import Flood
        from p2pnetwork_tpu.node import Node
        from p2pnetwork_tpu.sim import engine, failures
        from p2pnetwork_tpu.sim import graph as G

        # Populate the plane from BOTH backends, as a real process would.
        a = Node("127.0.0.1", 0, id="pa")
        b = Node("127.0.0.1", 0, id="pb")
        try:
            a.start()
            b.start()
            a.connect_with_node("127.0.0.1", b.port)
            assert _wait_until(lambda: len(b.nodes_inbound) == 1)
            a.send_to_nodes({"ping": 1})
            assert _wait_until(
                lambda: reg.value("p2p_messages_received_total", node="pb") >= 1)
            # identical shapes/statics to TestSimInstrumentation's run
            # -> jit cache hit, no second compile
            g = G.watts_strogatz(400, 4, 0.1, seed=0)
            engine.run_until_coverage(g, Flood(source=0), jax.random.key(0),
                                      coverage_target=0.99, max_rounds=64)
            failures.fail_nodes(g, [5])

            with telemetry.MetricsServer(reg, port=0) as srv:
                body = urllib.request.urlopen(srv.url, timeout=5) \
                    .read().decode()
                jbody = json.loads(
                    urllib.request.urlopen(srv.url + ".json", timeout=5)
                    .read().decode())
        finally:
            a.stop()
            b.stop()
            a.join(timeout=10)
            b.join(timeout=10)

        families = _assert_valid_exposition(body)
        expected = {
            "p2p_messages_sent_total", "p2p_messages_received_total",
            "p2p_bytes_sent_total", "p2p_bytes_received_total",
            "p2p_message_handle_seconds", "p2p_connections",
            "sim_runs_total", "sim_rounds_total", "sim_messages_total",
            "sim_injected_failures_total",
        }
        assert expected <= families
        assert len(families) >= 8
        assert set(jbody) == set(reg.snapshot())

    def test_unknown_path_is_404(self, reg):
        with telemetry.MetricsServer(reg, port=0) as srv:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=5)
            assert e.value.code == 404
