"""Fault injection: node/edge failures must consistently re-mask every
representation, and protocols must route around (or die in) the damage."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from p2pnetwork_tpu.models import SIR, Flood  # noqa: E402
from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def _brute_or(g, signal):
    emask = np.asarray(g.edge_mask)
    s = np.asarray(g.senders)[emask]
    r = np.asarray(g.receivers)[emask]
    sig = np.asarray(signal)
    out = np.zeros(g.n_nodes_padded, dtype=bool)
    for a, b in zip(s, r):
        out[b] |= sig[a]
    return out & np.asarray(g.node_mask)


class TestNodeFailures:
    def test_masks_consistent_across_representations(self):
        g = G.watts_strogatz(500, 6, 0.2, seed=0, blocked=True, hybrid=True)
        dead = [3, 77, 410]
        gf = failures.fail_nodes(g, dead)
        key = jax.random.key(0)
        sig = jax.random.bernoulli(key, 0.4, (g.n_nodes_padded,)) & gf.node_mask
        ref = _brute_or(gf, sig)
        for method in ("segment", "gather", "pallas", "hybrid"):
            out = np.asarray(segment.propagate_or(gf, sig, method))
            np.testing.assert_array_equal(out, ref, err_msg=method)

    def test_degrees_recomputed(self):
        g = G.ring(300)
        gf = failures.fail_nodes(g, [10])
        in_deg = np.asarray(gf.in_degree)
        assert in_deg[10] == 0
        assert in_deg[9] == 1 and in_deg[11] == 1  # lost the dead neighbor
        assert in_deg[100] == 2

    def test_dead_nodes_neither_send_nor_receive(self):
        g = G.ring(64)
        gf = failures.fail_nodes(g, [1])
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        out = np.asarray(segment.propagate_or(gf, sig, "segment"))
        assert not out[1]  # dead receiver
        sig2 = jnp.zeros(g.n_nodes_padded, dtype=bool).at[1].set(True)
        out2 = np.asarray(segment.propagate_or(gf, sig2, "segment"))
        assert not out2.any()  # dead sender

    def test_partition_stops_flood(self):
        # Cutting two bridge nodes of a ring partitions it: the flood
        # covers only the source's side.
        g = G.ring(100)
        gf = failures.fail_nodes(g, [25, 75])
        state, _ = engine.run(gf, Flood(source=0), jax.random.key(0), 100)
        seen = np.asarray(state.seen)[:100]
        assert seen[:25].all() and seen[76:].all()
        assert not seen[26:75].any()

    def test_original_graph_untouched(self):
        g = G.ring(128)
        _ = failures.fail_nodes(g, [5])
        assert int(np.asarray(g.node_mask).sum()) == 128
        assert np.asarray(g.in_degree)[5] == 2

    def test_random_failures_fraction(self):
        g = G.watts_strogatz(2000, 4, 0.1, seed=1)
        gf = failures.random_node_failures(g, jax.random.key(0), 0.3)
        alive = int(np.asarray(gf.node_mask).sum())
        assert 1250 < alive < 1550  # ~1400 expected

    def test_sir_dies_out_under_heavy_node_loss(self):
        g = G.watts_strogatz(1000, 4, 0.05, seed=2)
        gf = failures.random_node_failures(g, jax.random.key(1), 0.9)
        proto = SIR(beta=0.5, gamma=0.2, source=0, method="segment")
        state, stats = engine.run(gf, proto, jax.random.key(2), 30)
        # with 90% of nodes gone the epidemic cannot reach most of the graph
        assert float(np.asarray(stats["coverage"])[-1]) < 0.2


class TestEdgeFailures:
    def test_directed_cut_is_one_way(self):
        g = G.ring(64)
        emask = np.asarray(g.edge_mask)
        s = np.asarray(g.senders)
        r = np.asarray(g.receivers)
        (eid,) = np.nonzero(emask & (s == 0) & (r == 1))
        gf = failures.fail_edges(g, [int(eid[0])])
        sig0 = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        out = np.asarray(segment.propagate_or(gf, sig0, "segment"))
        assert not out[1]  # 0 -> 1 cut
        sig1 = jnp.zeros(g.n_nodes_padded, dtype=bool).at[1].set(True)
        out = np.asarray(segment.propagate_or(gf, sig1, "segment"))
        assert out[0]  # 1 -> 0 still alive

    def test_neighbor_table_stays_exact(self):
        g = G.watts_strogatz(400, 4, 0.2, seed=3)
        cut = np.nonzero(np.asarray(g.edge_mask))[0][::7]
        gf = failures.fail_edges(g, cut)
        sig = jax.random.bernoulli(jax.random.key(0), 0.3,
                                   (g.n_nodes_padded,)) & gf.node_mask
        ref = _brute_or(gf, sig)
        np.testing.assert_array_equal(
            np.asarray(segment.propagate_or(gf, sig, "gather")), ref
        )
        assert (np.asarray(gf.neighbor_mask).sum(axis=1)
                == np.asarray(gf.in_degree)).all()

    def test_rejects_blocked_hybrid_graphs(self):
        g = G.ring(300).with_hybrid()
        with pytest.raises(ValueError, match="fail_nodes"):
            failures.fail_edges(g, [0])

    def test_capped_table_dropped(self):
        src = np.arange(1, 20, dtype=np.int32)
        dst = np.zeros(19, dtype=np.int32)
        g = G.from_edges(src, dst, 20, max_degree=4)
        gf = failures.fail_edges(g, [0])
        assert gf.neighbors is None  # slot->edge map lost; table dropped

    def test_random_edge_failures(self):
        g = G.watts_strogatz(1000, 6, 0.1, seed=4)
        gf = failures.random_edge_failures(g, jax.random.key(0), 0.5)
        n_alive = int(np.asarray(gf.edge_mask).sum())
        assert 0.4 * g.n_edges < n_alive < 0.6 * g.n_edges
        # degree bookkeeping still exact
        emask = np.asarray(gf.edge_mask)
        r = np.asarray(gf.receivers)[emask]
        np.testing.assert_array_equal(
            np.bincount(r, minlength=gf.n_nodes_padded),
            np.asarray(gf.in_degree),
        )


def test_coverage_stays_bounded_after_churn():
    # Regression: dead-but-seen nodes pushed flood coverage past 1.0 and
    # made run-to-coverage exit spuriously at round 0 after heavy churn.
    g = G.ring(100)
    proto = Flood(source=0)
    state, _ = engine.run(g, proto, jax.random.key(0), 60)  # fully flooded
    gf = failures.random_node_failures(g, jax.random.key(1), 0.5)
    cov = float(proto.coverage(gf, state))
    assert 0.0 <= cov <= 1.0
    _, stats = engine.run_from(gf, proto, state, jax.random.key(0), 3)
    assert (np.asarray(stats["coverage"]) <= 1.0).all()


def test_out_of_range_ids_raise():
    g = G.ring(128)
    with pytest.raises(ValueError, match="node id out of range"):
        failures.fail_nodes(g, [500])
    with pytest.raises(ValueError, match="edge id out of range"):
        failures.fail_edges(g, [-1])


def test_churn_mid_run_resumes():
    # Kill nodes between rounds and continue from the same protocol state —
    # the sim-side analog of peers dropping mid-broadcast.
    g = G.watts_strogatz(1000, 6, 0.1, seed=6)
    proto = Flood(source=0)
    key = jax.random.key(0)
    state, _ = engine.run(g, proto, key, 3)
    gf = failures.random_node_failures(g, jax.random.key(7), 0.4)
    # Nodes that already saw the message but died stop counting/forwarding.
    # donate=False: this test reads the pre-resume state again below.
    state2, stats = engine.run_from(gf, proto, state, key, 12, donate=False)
    seen = np.asarray(state2.seen)
    alive = np.asarray(gf.node_mask)
    dead_new = seen & ~alive & (np.arange(seen.size) < 1000)
    # dead nodes never gain the message after the cut
    seen_before = np.asarray(state.seen)
    assert (seen_before | alive)[dead_new].all() if dead_new.any() else True
    # the surviving component still makes progress
    assert float(np.asarray(stats["coverage"])[-1]) > 0.5


def test_failures_compose():
    g = G.watts_strogatz(600, 6, 0.2, seed=5)
    gf = failures.fail_edges(g, [0, 5, 9])
    gf = failures.fail_nodes(gf, [100, 200])
    sig = jax.random.bernoulli(jax.random.key(1), 0.3,
                               (g.n_nodes_padded,)) & gf.node_mask
    ref = _brute_or(gf, sig)
    np.testing.assert_array_equal(
        np.asarray(segment.propagate_or(gf, sig, "segment")), ref
    )
    np.testing.assert_array_equal(
        np.asarray(segment.propagate_or(gf, sig, "gather")), ref
    )

class TestChaosNameParity:
    """The sockets chaos plane (chaos/plane.py) mirrors this module
    name-for-name; the shared vocabulary must work sim-side too."""

    def test_kill_and_cut_aliases(self):
        g = G.ring(64)
        np.testing.assert_array_equal(
            np.asarray(failures.kill_nodes(g, [3]).node_mask),
            np.asarray(failures.fail_nodes(g, [3]).node_mask))
        np.testing.assert_array_equal(
            np.asarray(failures.cut_links(g, [7]).edge_mask),
            np.asarray(failures.fail_edges(g, [7]).edge_mask))

    def test_partition_cuts_only_crossing_edges(self):
        g = G.ring(8)  # directed ring: edges i -> i+1 and i -> i-1
        gp = failures.partition(g, [[0, 1, 2, 3], [4, 5, 6, 7]])
        s = np.asarray(g.senders)
        r = np.asarray(g.receivers)
        side = np.where(np.arange(g.n_nodes_padded) < 4, 0, 1)
        crossing = (side[s] != side[r]) & np.asarray(g.edge_mask)
        emask = np.asarray(gp.edge_mask)
        assert not emask[crossing].any()
        within = ~crossing & np.asarray(g.edge_mask)
        np.testing.assert_array_equal(emask[within],
                                      np.asarray(g.edge_mask)[within])
        # A flood from node 0 covers only its side.
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[0].set(True)
        for _ in range(8):
            sig = sig | segment.propagate_or(gp, sig, "segment")
        out = np.asarray(sig)[:8]
        assert out[:4].all() and not out[4:].any()

    def test_partition_leaves_ungrouped_nodes_connected(self):
        g = G.ring(8)
        gp = failures.partition(g, [[0, 1, 2], [5, 6, 7]])  # 3, 4 ungrouped
        emask = np.asarray(gp.edge_mask)
        s = np.asarray(g.senders)
        r = np.asarray(g.receivers)
        # 2 -> 3 and 3 -> 4 cross into/out of the ungrouped gap: alive.
        bridge = ((s == 2) & (r == 3)) | ((s == 3) & (r == 4))
        assert emask[bridge & np.asarray(g.edge_mask)].all()

    def test_revive_restores_original_wiring(self):
        g = G.ring(64)
        gf = failures.kill_nodes(g, [3, 10])
        gr = failures.revive_nodes(gf, [3], g)
        alive = np.asarray(gr.node_mask)
        assert alive[3] and not alive[10]
        # 3's ring edges came back; 10's stayed dead.
        assert np.asarray(gr.in_degree)[3] == 2
        assert np.asarray(gr.in_degree)[10] == 0
        # Full revival round-trips to the original graph.
        g2 = failures.revive_nodes(gf, [3, 10], g)
        np.testing.assert_array_equal(np.asarray(g2.node_mask),
                                      np.asarray(g.node_mask))
        np.testing.assert_array_equal(np.asarray(g2.edge_mask),
                                      np.asarray(g.edge_mask))
        np.testing.assert_array_equal(np.asarray(g2.in_degree),
                                      np.asarray(g.in_degree))

    def test_partition_cuts_dynamic_links_too(self):
        from p2pnetwork_tpu.sim import topology

        g = topology.with_capacity(G.ring(8), extra_edges=4)
        g = topology.connect(g, [1], [6])  # runtime link spanning the split
        gp = failures.partition(g, [[0, 1, 2, 3], [4, 5, 6, 7]])
        assert int(np.asarray(gp.dyn_mask).sum()) == 0  # both directions dead
        sig = jnp.zeros(g.n_nodes_padded, dtype=bool).at[1].set(True)
        for _ in range(8):
            sig = sig | segment.propagate_or(gp, sig, "segment")
        out = np.asarray(sig)[:8]
        assert out[:4].all() and not out[4:].any()
        # Same-side dynamic links survive a partition.
        g2 = topology.connect(topology.with_capacity(G.ring(8), extra_edges=4),
                              [0], [2])
        gp2 = failures.partition(g2, [[0, 1, 2, 3], [4, 5, 6, 7]])
        assert int(np.asarray(gp2.dyn_mask).sum()) == 2
