"""Per-axis collective placement on the hierarchical (DCN x ICI) meshes.

SURVEY section 5 names ICI-within-slice / DCN-across-slices as the
designated 10M+ scaling path; these tests make that claim EVIDENCE on an
emulated 2-host x 4-chip layout over the suite's 8 virtual CPU devices
(mesh_2d(hosts=2) — axis semantics, not wire speed, are under test):

- the ICI-major sharded RING: each round's collective-permute hops are
  rank -> rank+1, so exactly ``n_hosts`` of the ``S`` hop pairs cross a
  host boundary (the DCN hops) and the other ``S - n_hosts`` stay inside
  a host's ICI domain — the structural property that makes the
  hierarchical ring's DCN bill 1/per_host of its hop traffic;
- the GSPMD auto path on the 2-D mesh with node/edge axes on ``ici``:
  decoded replica groups + permute pairs bound the cross-DCN bytes of
  the whole compiled module to one node-extent array — O(N) where an
  edge-extent re-shard would be O(E). (The emulated mesh gives XLA no
  DCN cost model, so it spreads partial work across the pool; explicit
  hierarchical placement is the ring path's job, pinned above.)

Both decoders handle XLA's iota replica-group form
(``[G,S]<=[dims]T(perm)``) and the literal form (``{{0,1},{2,3}}``).
"""

import re

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import auto, multihost, sharded  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402

from tests.test_auto_comm import _collectives, _LINE  # noqa: E402

N_HOSTS, PER_HOST = 2, 4

_IOTA = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_LITERAL = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_PAIRS = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")


def _decode_groups(line):
    """Replica groups of one HLO collective line as a list of tuples."""
    m = _IOTA.search(line)
    if m:
        ng, gs = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = ([int(d) for d in m.group(4).split(",")]
                if m.group(4) else list(range(len(dims))))
        devs = np.arange(int(np.prod(dims))).reshape(dims).transpose(perm)
        return [tuple(g) for g in devs.reshape(ng, gs)]
    m = _LITERAL.search(line)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in m.group(1).strip("{}").split("},{")]
    return []


def _host_of(device_id: int) -> int:
    return device_id // PER_HOST


def _crosses_host(group) -> bool:
    return len({_host_of(d) for d in group}) > 1


def _permute_pairs(line):
    """source->target pairs of one collective-permute HLO line."""
    m = _PAIRS.search(line)
    if not m:
        return []
    return [tuple(int(x) for x in p.split(","))
            for p in m.group(1).strip("{}").split("},{")]


def classify_collective_bytes(hlo: str):
    """``(ici_bytes, dcn_bytes)`` over every collective in the module —
    replica-group collectives classified by decoded groups,
    collective-permutes by their source->target pairs (permutes carry no
    replica_groups, and skipping them would blind the DCN budget to
    cross-host permute traffic). Shared by the placement tests and
    examples/hierarchical_mesh_demo.py so the printed facts and the
    pinned assertions cannot drift."""
    ici = dcn = 0
    for ln in hlo.splitlines():
        if not _LINE.search(ln):
            continue
        groups = _decode_groups(ln)
        pairs = _permute_pairs(ln)
        if not groups and not pairs:
            continue
        nbytes = sum(c[3] for c in _collectives(ln))
        crossing = (any(_crosses_host(g) for g in groups)
                    or any(_host_of(a) != _host_of(b) for a, b in pairs))
        if crossing:
            dcn += nbytes
        else:
            ici += nbytes
    return ici, dcn


def ring_hop_classes(hlo: str):
    """``(ici_hops, dcn_hops, permute_pair_lists)`` over every
    collective-permute of a compiled ring program."""
    ici = dcn = 0
    per_permute = []
    for ln in hlo.splitlines():
        if "collective-permute" not in ln:
            continue
        pairs = _permute_pairs(ln)
        if not pairs:
            continue
        per_permute.append(pairs)
        for a, b in pairs:
            if _host_of(a) == _host_of(b):
                ici += 1
            else:
                dcn += 1
    return ici, dcn, per_permute


def lower_ring_flood_hlo(n=1024, rounds=3):
    """Compile the real sharded ring flood over the 8-device ring mesh
    and return its HLO text (shared with the demo)."""
    g = G.watts_strogatz(n, 6, 0.2, seed=0)
    mesh = M.ring_mesh(8)
    sg = sharded.shard_graph(g, mesh)
    fn = sharded._flood_fn(mesh, mesh.axis_names[0], sg.n_shards,
                           sg.block, rounds, sg.diag_pieces, sg.mxu_block)
    seen0 = sharded._flood_seed(sg, 0)
    return fn.lower(
        sg.bkt_src, sg.bkt_dst, sg.bkt_mask, *sharded._dyn_or_empty(sg),
        *sharded._mxu_or_empty(sg), sharded._diag_masks_or_empty(sg),
        sg.node_mask, sg.out_degree, seen0, seen0,
    ).compile().as_text()


class TestRingHopPlacement:
    def test_permute_hops_cross_dcn_exactly_n_hosts_times(self):
        # The ICI-major ring: lower the real sharded flood program and
        # read every collective-permute's source->target pairs.
        hlo = lower_ring_flood_hlo()
        ici, dcn, per_permute = ring_hop_classes(hlo)
        assert per_permute, "ring program lowered without collective-permutes"
        S = 8
        for pairs in per_permute:
            # Every hop is rank -> rank+1 (mod S): the ring structure.
            assert sorted(pairs) == sorted(
                [(i, (i + 1) % S) for i in range(S)]), pairs
        # Exactly one boundary hop per host per permute: DCN carries
        # 1/per_host of the ring's hop traffic, ICI the rest.
        assert dcn == N_HOSTS * len(per_permute), (ici, dcn)
        assert ici == (S - N_HOSTS) * len(per_permute)


class TestMesh2dAutoPlacement:
    def _hlo(self, protocol, n=4096, rounds=5):
        g = G.watts_strogatz(n, 6, 0.2, seed=0)
        mesh = multihost.mesh_2d(hosts=N_HOSTS)
        assert mesh.devices.shape == (N_HOSTS, PER_HOST)
        gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
        return g, engine.run.lower(
            gs, protocol, jax.random.key(0), rounds).compile().as_text()

    def test_dcn_traffic_bounded_by_node_extent(self):
        # Honest form of the hierarchy claim for the AUTO path: the CPU
        # emulation gives XLA no DCN cost model, so it freely spreads
        # partial work across the whole pool (measured: cross-host bytes
        # roughly match in-row bytes on this program — the explicit ring
        # path, not auto, is where placement is controlled, see
        # TestRingHopPlacement). What the auto path DOES guarantee, and
        # what keeps it DCN-sane at scale: the protocol's collectives are
        # node-extent, so the total cross-DCN bytes of the compiled
        # module stay within one node-extent array — O(N), never the
        # O(E) an edge-extent re-shard would cost.
        g, hlo = self._hlo(Flood(source=0, method="segment"))
        ici_bytes, dcn_bytes = classify_collective_bytes(hlo)
        assert ici_bytes > 0, "nothing placed on the ICI axis"
        assert dcn_bytes <= g.n_nodes_padded * 4, (
            f"DCN carries {dcn_bytes} bytes — more than one node-extent "
            f"array ({g.n_nodes_padded * 4})")

    def test_results_match_engine_on_2d_mesh(self):
        g = G.watts_strogatz(2048, 6, 0.2, seed=0)
        mesh = multihost.mesh_2d(hosts=N_HOSTS)
        gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
        st_a, _ = auto.run_auto(gs, Flood(source=0, method="segment"),
                                jax.random.key(0), 6)
        st_r, _ = engine.run(g, Flood(source=0, method="segment"),
                             jax.random.key(0), 6)
        np.testing.assert_array_equal(np.asarray(st_a.seen),
                                      np.asarray(st_r.seen))

    def test_collectives_never_exceed_node_extent(self):
        g, hlo = self._hlo(Flood(source=0, method="segment"))
        colls = _collectives(hlo)
        assert colls
        for op, dtype, shape, nbytes in colls:
            assert nbytes <= g.n_nodes_padded * 4, (
                f"{op} moves {nbytes} bytes — edge-extent traffic on the "
                f"2-D mesh")
