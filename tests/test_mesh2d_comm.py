"""Per-axis collective placement on the hierarchical (DCN x ICI) meshes.

SURVEY section 5 names ICI-within-slice / DCN-across-slices as the
designated 10M+ scaling path; these tests make that claim EVIDENCE on an
emulated 2-host x 4-chip layout over the suite's 8 virtual CPU devices
(mesh_2d(hosts=2) — axis semantics, not wire speed, are under test):

- the ICI-major sharded RING: each round's collective-permute hops are
  rank -> rank+1, so exactly ``n_hosts`` of the ``S`` hop pairs cross a
  host boundary (the DCN hops) and the other ``S - n_hosts`` stay inside
  a host's ICI domain — the structural property that makes the
  hierarchical ring's DCN bill 1/per_host of its hop traffic;
- the GSPMD auto path on the 2-D mesh with node/edge axes on ``ici``:
  decoded replica groups + permute pairs bound the cross-DCN bytes of
  the whole compiled module to one node-extent array — O(N) where an
  edge-extent re-shard would be O(E). (The emulated mesh gives XLA no
  DCN cost model, so it spreads partial work across the pool; explicit
  hierarchical placement is the ring path's job, pinned above.)

Both decoders handle XLA's iota replica-group form
(``[G,S]<=[dims]T(perm)``) and the literal form (``{{0,1},{2,3}}``).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from p2pnetwork_tpu.models import Flood  # noqa: E402
from p2pnetwork_tpu.parallel import auto, commviz, multihost  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402

N_HOSTS, PER_HOST = 2, 4


def _host_of(device_id: int) -> int:
    return device_id // PER_HOST


def classify_collective_bytes(hlo):
    """(ici_bytes, dcn_bytes) under this module's emulated 2x4 layout."""
    return commviz.classify_collective_bytes(hlo, _host_of)


def ring_hop_classes(hlo):
    return commviz.ring_hop_classes(hlo, _host_of)


lower_ring_flood_hlo = commviz.lower_ring_flood_hlo


class TestRingHopPlacement:
    def test_permute_hops_cross_dcn_exactly_n_hosts_times(self):
        # The ICI-major ring: lower the real sharded flood program and
        # read every collective-permute's source->target pairs.
        hlo = lower_ring_flood_hlo()
        ici, dcn, per_permute = ring_hop_classes(hlo)
        assert per_permute, "ring program lowered without collective-permutes"
        S = 8
        for pairs in per_permute:
            # Every hop is rank -> rank+1 (mod S): the ring structure.
            assert sorted(pairs) == sorted(
                [(i, (i + 1) % S) for i in range(S)]), pairs
        # Exactly one boundary hop per host per permute: DCN carries
        # 1/per_host of the ring's hop traffic, ICI the rest.
        assert dcn == N_HOSTS * len(per_permute), (ici, dcn)
        assert ici == (S - N_HOSTS) * len(per_permute)


class TestMesh2dAutoPlacement:
    def _hlo(self, protocol, n=4096, rounds=5):
        g = G.watts_strogatz(n, 6, 0.2, seed=0)
        mesh = multihost.mesh_2d(hosts=N_HOSTS)
        assert mesh.devices.shape == (N_HOSTS, PER_HOST)
        gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
        return g, engine.run.lower(
            gs, protocol, jax.random.key(0), rounds).compile().as_text()

    def test_dcn_traffic_bounded_by_node_extent(self):
        # Honest form of the hierarchy claim for the AUTO path: the CPU
        # emulation gives XLA no DCN cost model, so it freely spreads
        # partial work across the whole pool (measured: cross-host bytes
        # roughly match in-row bytes on this program — the explicit ring
        # path, not auto, is where placement is controlled, see
        # TestRingHopPlacement). What the auto path DOES guarantee, and
        # what keeps it DCN-sane at scale: the protocol's collectives are
        # node-extent, so the total cross-DCN bytes of the compiled
        # module stay within one node-extent array — O(N), never the
        # O(E) an edge-extent re-shard would cost.
        g, hlo = self._hlo(Flood(source=0, method="segment"))
        ici_bytes, dcn_bytes = classify_collective_bytes(hlo)
        assert ici_bytes > 0, "nothing placed on the ICI axis"
        assert dcn_bytes <= g.n_nodes_padded * 4, (
            f"DCN carries {dcn_bytes} bytes — more than one node-extent "
            f"array ({g.n_nodes_padded * 4})")

    def test_results_match_engine_on_2d_mesh(self):
        g = G.watts_strogatz(2048, 6, 0.2, seed=0)
        mesh = multihost.mesh_2d(hosts=N_HOSTS)
        gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
        st_a, _ = auto.run_auto(gs, Flood(source=0, method="segment"),
                                jax.random.key(0), 6)
        st_r, _ = engine.run(g, Flood(source=0, method="segment"),
                             jax.random.key(0), 6)
        np.testing.assert_array_equal(np.asarray(st_a.seen),
                                      np.asarray(st_r.seen))

    def test_collectives_never_exceed_node_extent(self):
        g, hlo = self._hlo(Flood(source=0, method="segment"))
        colls = commviz.collectives(hlo)
        assert colls
        for op, dtype, shape, nbytes in colls:
            assert nbytes <= g.n_nodes_padded * 4, (
                f"{op} moves {nbytes} bytes — edge-extent traffic on the "
                f"2-D mesh")


class TestDecoderUnits:
    def test_iota_form_with_transpose(self):
        line = ("%ar = pred[64]{0} all-reduce(%x), channel_id=1, "
                "replica_groups=[4,2]<=[2,4]T(1,0), to_apply=%add")
        assert commviz.decode_groups(line) == [
            (0, 4), (1, 5), (2, 6), (3, 7)]

    def test_iota_form_identity_perm(self):
        line = ("%ag = pred[64]{0} all-gather(%x), channel_id=2, "
                "replica_groups=[2,4]<=[8], dimensions={0}")
        assert commviz.decode_groups(line) == [
            (0, 1, 2, 3), (4, 5, 6, 7)]

    def test_literal_form(self):
        line = ("%ar = f32[8]{0} all-reduce(%x), channel_id=3, "
                "replica_groups={{0,2},{1,3}}, to_apply=%add")
        assert commviz.decode_groups(line) == [(0, 2), (1, 3)]

    def test_permute_pairs(self):
        line = ("%cp = s32[16]{0} collective-permute(%x), channel_id=4, "
                "source_target_pairs={{0,1},{1,2},{2,0}}")
        assert commviz.permute_pairs(line) == [(0, 1), (1, 2), (2, 0)]

    def test_classify_counts_permute_bytes(self):
        # The regression the shared helper exists for: permutes carry no
        # replica_groups, and skipping them would blind the cross-host
        # budget to permute traffic.
        hlo = ("%cp = s32[256]{0} collective-permute(%x), channel_id=1, "
               "source_target_pairs={{0,4}}\n"
               "%ar = pred[128]{0} all-reduce(%y), channel_id=2, "
               "replica_groups=[2,4]<=[8], to_apply=%add\n")
        within, cross = commviz.classify_collective_bytes(
            hlo, lambda d: d // 4)
        assert cross == 256 * 4  # the permute crosses hosts
        assert within == 128  # the in-row reduce
