"""Chandy-Lamport snapshots over real loopback sockets.

The headline oracle is conservation: a fixed number of tokens circulates
among three live nodes while snapshots are taken mid-stream; ANY
consistent cut must account for exactly that many tokens across recorded
node states + recorded channel states, for every interleaving the real
sockets produce. Plus the state machine's edges: markers never reach
app_message, duplicate/unknown markers are inert, a peerless snapshot
completes immediately, and a peer dying mid-snapshot releases its
channel instead of hanging the cut.
"""

import threading

from p2pnetwork_tpu import SnapshotNode
from p2pnetwork_tpu.snapshot import MARKER_KEY
from tests.helpers import stop_all, wait_until

HOST = "127.0.0.1"


class TokenNode(SnapshotNode):
    """Holds tokens; all mutation happens on the event loop (handlers and
    posted movers), per the snapshot atomicity contract."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tokens = 0
        self.app_seen = []

    def capture_state(self):
        return {"tokens": self.tokens}

    def app_message(self, node, data):
        self.app_seen.append(data)
        if isinstance(data, dict) and "token" in data:
            self.tokens += data["token"]

    def move_token(self, to_node):
        """Post a one-token transfer to ``to_node`` onto the loop: the
        decrement and the send land atomically w.r.t. any cut."""

        def _do():
            if self.tokens > 0:
                self.tokens -= 1
                peers = [c for c in self.all_nodes if c.id == to_node.id]
                if peers:
                    self.send_to_node(peers[0], {"token": 1})
                else:  # peer gone: put it back rather than destroy it
                    self.tokens += 1

        self.post(_do)


def _triangle(cls=TokenNode):
    a = cls(HOST, 0, id="A")
    b = cls(HOST, 0, id="B")
    c = cls(HOST, 0, id="C")
    for n in (a, b, c):
        n.start()
    assert a.connect_with_node(HOST, b.port)
    assert b.connect_with_node(HOST, c.port)
    assert c.connect_with_node(HOST, a.port)
    assert wait_until(lambda: all(len(n.all_nodes) == 2 for n in (a, b, c)))
    return a, b, c


class TestSnapshotBasics:
    def test_peerless_snapshot_completes_immediately(self):
        a = TokenNode(HOST, 0, id="solo")
        a.start()
        try:
            sid = a.take_snapshot()
            snap = a.wait_snapshot(sid, timeout=5.0)
            assert snap is not None
            assert snap["state"] == {"tokens": 0}
            assert snap["channels"] == {}
        finally:
            stop_all([a])

    def test_markers_never_reach_app_message(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            sid = a.take_snapshot()
            for n in nodes:
                assert n.wait_snapshot(sid, timeout=10.0) is not None
            for n in nodes:
                assert not any(
                    isinstance(m, dict) and MARKER_KEY in m
                    for m in n.app_seen
                ), f"marker leaked to app_message on {n.id}"
        finally:
            stop_all(nodes)

    def test_all_nodes_complete_with_empty_channels_when_idle(self):
        nodes = _triangle()
        try:
            sid = nodes[1].take_snapshot()
            for n in nodes:
                snap = n.wait_snapshot(sid, timeout=10.0)
                assert snap is not None
                assert snap["state"] == {"tokens": 0}
                # Idle network: every recorded channel is empty.
                assert all(msgs == [] for msgs in snap["channels"].values())
                assert len(snap["channels"]) == 2
        finally:
            stop_all(nodes)

    def test_snapshot_complete_event_dispatched(self):
        events = []

        def cb(event, main_node, connected_node, data):
            events.append((event, data))

        a = TokenNode(HOST, 0, id="solo", callback=cb)
        a.start()
        try:
            sid = a.take_snapshot()
            assert a.wait_snapshot(sid, timeout=5.0) is not None
            assert any(e == "snapshot_complete" and d["id"] == sid
                       for e, d in events)
        finally:
            stop_all([a])


class TestTokenConservation:
    TOTAL = 12

    def test_conservation_under_churn_of_messages(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            a.post(lambda: setattr(a, "tokens", self.TOTAL))
            assert wait_until(lambda: a.tokens == self.TOTAL)

            stop_flag = threading.Event()

            def pump():
                ring = [(a, b), (b, c), (c, a), (a, c), (c, b), (b, a)]
                i = 0
                while not stop_flag.is_set():
                    src, dst = ring[i % len(ring)]
                    src.move_token(dst)
                    i += 1

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            try:
                sids = [n.take_snapshot() for n in (a, b, c)]
                snaps = []
                for sid in sids:
                    for n in nodes:
                        snap = n.wait_snapshot(sid, timeout=15.0)
                        assert snap is not None, \
                            f"snapshot {sid} never completed on {n.id}"
                        snaps.append(snap)
            finally:
                stop_flag.set()
                t.join(timeout=5.0)

            for sid in sids:
                cut = [s for s in snaps if s["id"] == sid]
                assert len(cut) == 3
                in_states = sum(s["state"]["tokens"] for s in cut)
                in_flight = sum(
                    m.get("token", 0)
                    for s in cut
                    for msgs in s["channels"].values()
                    for m in msgs
                    if isinstance(m, dict)
                )
                assert in_states + in_flight == self.TOTAL, (
                    f"cut {sid}: {in_states} in states + {in_flight} "
                    f"in flight != {self.TOTAL}"
                )
        finally:
            stop_all(nodes)


class TestSnapshotEdges:
    def test_duplicate_and_unknown_markers_are_inert(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            sid = a.take_snapshot()
            for n in nodes:
                assert n.wait_snapshot(sid, timeout=10.0) is not None
            # Re-delivering markers for a finished id must not resurrect it.
            b.send_to_nodes({MARKER_KEY: sid})
            done = b.get_snapshot(sid)
            assert wait_until(lambda: b.get_snapshot(sid) is done)
            assert a.get_snapshot(sid) is not None
        finally:
            stop_all(nodes)

    def test_dead_peer_releases_channel_mid_cut(self):
        # C is a PLAIN reference-style Node: it never answers with a
        # marker, so A's snapshot genuinely stalls on the A<-C channel
        # (the mid-cut state) until C dies — then the release path must
        # complete the cut WITH the app message C sent while recording.
        from p2pnetwork_tpu import Node

        a = TokenNode(HOST, 0, id="A")
        b = TokenNode(HOST, 0, id="B")
        c = Node(HOST, 0, id="C")
        nodes = [a, b, c]
        try:
            for n in nodes:
                n.start()
            assert a.connect_with_node(HOST, b.port)
            assert b.connect_with_node(HOST, c.port)
            assert c.connect_with_node(HOST, a.port)
            assert wait_until(
                lambda: all(len(n.all_nodes) == 2 for n in nodes))
            sid = a.take_snapshot()
            # Both A and B stall mid-cut: each has a C channel that will
            # never deliver a marker while C lives.
            assert a.wait_snapshot(sid, timeout=0.5) is None
            assert b.wait_snapshot(sid, timeout=0.5) is None
            # Traffic from C while A records that channel -> channel state.
            c.send_to_nodes({"token": 1})
            assert wait_until(lambda: len(a.app_seen) > 0)
            c.stop()
            c.join(timeout=10.0)
            snap = a.wait_snapshot(sid, timeout=10.0)
            assert snap is not None, "snapshot hung on the dead channel"
            assert {"token": 1} in snap["channels"].get("C", [])
            assert b.wait_snapshot(sid, timeout=10.0) is not None
        finally:
            stop_all(nodes)

    def test_reused_snapshot_id_rejected(self):
        a = TokenNode(HOST, 0, id="solo")
        a.start()
        try:
            sid = a.take_snapshot("cut-1")
            assert a.wait_snapshot(sid, timeout=5.0) is not None
            import pytest as _pytest
            with _pytest.raises(ValueError):
                a.take_snapshot("cut-1")
        finally:
            stop_all([a])

    def test_discard_releases_retention(self):
        a = TokenNode(HOST, 0, id="solo")
        a.start()
        try:
            sid = a.take_snapshot()
            assert a.wait_snapshot(sid, timeout=5.0) is not None
            snap = a.discard_snapshot(sid)
            assert snap is not None and snap["id"] == sid
            assert wait_until(lambda: a.get_snapshot(sid) is None)
        finally:
            stop_all([a])

    def test_concurrent_snapshot_ids_interleave(self):
        nodes = _triangle()
        a, b, c = nodes
        try:
            sid1 = a.take_snapshot()
            sid2 = b.take_snapshot()
            for sid in (sid1, sid2):
                for n in nodes:
                    assert n.wait_snapshot(sid, timeout=10.0) is not None
            assert sid1 != sid2
        finally:
            stop_all(nodes)
