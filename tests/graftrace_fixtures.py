"""Deliberately-racy / clean-twin fixture bodies for graftrace tests.

One pair per happens-before edge kind the detector derives:
release→acquire (lock), thread start, thread join, event set→wait, and
queue put→get. Each racy body carries exactly one ``# RACY`` marker on
the access the detector must anchor its finding at — the tests assert
the finding's ``file:line`` equals that marker's line, pinning not just
"a race was found" but "found at the right source line". Clean twins
differ only by the synchronization that orders the same accesses.

Also registered as (non-builtin) graftrace scenarios so the CLI tests
can drive them through ``--scenarios-from`` and prove the nonzero exit.
"""

from p2pnetwork_tpu import concurrency
from p2pnetwork_tpu.analysis.race import Shared
from p2pnetwork_tpu.analysis.race.scenarios import scenario


def _pair(target_a, target_b):
    t1 = concurrency.thread(target=target_a, name="A")
    t2 = concurrency.thread(target=target_b, name="B")
    t1.start()
    t2.start()
    t1.join()
    t2.join()


# ---------------------------------------------------------- lock edge

def lock_racy():
    cell = Shared(0, label="cell")
    lk = concurrency.lock()

    def a():
        with lk:
            cell.set(cell.get() + 1)

    def b():
        cell.set(5)  # RACY

    _pair(a, b)


def lock_clean():
    cell = Shared(0, label="cell")
    lk = concurrency.lock()

    def a():
        with lk:
            cell.set(cell.get() + 1)

    def b():
        with lk:
            cell.set(5)

    _pair(a, b)


# --------------------------------------------------------- start edge

def start_racy():
    cell = Shared(0, label="cell")

    def r():
        cell.get()

    t = concurrency.thread(target=r, name="R")
    t.start()
    cell.set(1)  # RACY
    t.join()


def start_clean():
    cell = Shared(0, label="cell")
    cell.set(1)  # before start: ordered by the spawn edge

    def r():
        cell.get()

    t = concurrency.thread(target=r, name="R")
    t.start()
    t.join()


# ---------------------------------------------------------- join edge

def join_racy():
    cell = Shared(0, label="cell")

    def w():
        cell.set(1)

    t = concurrency.thread(target=w, name="W")
    t.start()
    cell.get()  # RACY
    t.join()


def join_clean():
    cell = Shared(0, label="cell")

    def w():
        cell.set(1)

    t = concurrency.thread(target=w, name="W")
    t.start()
    t.join()
    cell.get()  # after join: ordered by the join edge


# --------------------------------------------------------- event edge

def event_racy():
    cell = Shared(0, label="cell")
    ev = concurrency.event()

    def w():
        cell.set(1)
        ev.set()

    def r():
        cell.get()  # RACY

    _pair(w, r)


def event_clean():
    cell = Shared(0, label="cell")
    ev = concurrency.event()

    def w():
        cell.set(1)
        ev.set()

    def r():
        ev.wait()
        cell.get()  # ordered by set -> wait

    _pair(w, r)


# --------------------------------------------------------- queue edge

def queue_racy():
    cell = Shared(0, label="cell")
    q = concurrency.fifo_queue()

    def p():
        cell.set(1)
        q.put("token")

    def c():
        cell.get()  # RACY

    _pair(p, c)


def queue_clean():
    cell = Shared(0, label="cell")
    q = concurrency.fifo_queue()

    def p():
        cell.set(1)
        q.put("token")

    def c():
        q.get()
        cell.get()  # ordered by put -> get

    _pair(p, c)


TWINS = {
    "lock": (lock_racy, lock_clean),
    "start": (start_racy, start_clean),
    "join": (join_racy, join_clean),
    "event": (event_racy, event_clean),
    "queue": (queue_racy, queue_clean),
}


# CLI-drivable registrations (non-builtin: never part of the CI gate).

@scenario("fixture_lock_racy",
          "deliberately racy lock twin (test fixture)", builtin=False)
def _fixture_lock_racy():
    return lock_racy


@scenario("fixture_lock_clean",
          "clean lock twin (test fixture)", builtin=False)
def _fixture_lock_clean():
    return lock_clean
