"""Shared test helpers for the sockets backend.

The reference synchronizes its integration tests with hard-coded
``time.sleep`` barriers (SURVEY.md section 4), which makes them slow and
flaky. These helpers replace the sleeps with condition polling with a real
deadline."""

from __future__ import annotations

import time
from typing import Callable, List


def wait_until(predicate: Callable[[], bool], timeout: float = 5.0,
               interval: float = 0.01) -> bool:
    """Poll ``predicate`` until it is true or ``timeout`` elapses."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def stop_all(nodes) -> None:
    """Stop and join a set of nodes (stop() is idempotent by contract).

    Nodes that were never start()ed are only stopped: Node is a real
    threading.Thread now, and joining an unstarted thread raises."""
    for n in nodes:
        n.stop()
    for n in nodes:
        if n.ident is not None:
            n.join(timeout=10.0)


def run_auto_parity(g, protocol, rounds, key_seed=0):
    """Shared recipe of the per-protocol GSPMD auto-parity tests: run
    ``protocol`` over the full-device ring mesh on the auto path and on
    the single-device engine with the same key, returning both final
    states for the caller's field assertions. Skips on a single device.
    Imports lazily so the sockets-only tests keep importing this module
    without jax."""
    import jax
    import pytest

    from p2pnetwork_tpu.parallel import auto
    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.sim import engine

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = M.ring_mesh(len(jax.devices()))
    ga = auto.shard_graph_auto(g, mesh)
    st_a, _ = auto.run_auto(ga, protocol, jax.random.key(key_seed), rounds)
    st_r, _ = engine.run(g, protocol, jax.random.key(key_seed), rounds)
    return st_a, st_r


class EventRecorder:
    """Callback that records (event, connected_id, data) tuples in order."""

    def __init__(self):
        self.events: List[tuple] = []

    def __call__(self, event, main_node, connected_node, data):
        cid = getattr(connected_node, "id", None)
        self.events.append((event, cid, data))

    def names(self) -> List[str]:
        return [e[0] for e in self.events]

    def count(self, name: str) -> int:
        return sum(1 for e in self.events if e[0] == name)

    def data_for(self, name: str) -> List:
        return [e[2] for e in self.events if e[0] == name]

    def messages(self) -> List:
        """Payloads of the node_message events, in delivery order."""
        return self.data_for("node_message")
