#!/bin/sh
# One command for a healthy-chip measurement session: the headline bench
# (writes the driver-format JSON line last), then the full scale ladder
# including the 10M row. Logs land next to this script with timestamps so
# BENCH.md can be refreshed from them afterwards.
#
#   sh benchmarks/run_on_chip.sh
#
# bench.py probes the backend first (subprocess, retry window) and emits
# an error JSON instead of hanging if the device tunnel is wedged; its
# exit code gates the ladder (POSIX sh has no pipefail, so capture the
# status before tee-ing the output).
set -u
cd "$(dirname "$0")/.."
stamp=$(date +%Y%m%d-%H%M%S)
log="benchmarks/chip-$stamp.log"
tmp="benchmarks/.chip-$stamp.tmp"
echo "# chip session $stamp" | tee "$log"
python bench.py > "$tmp" 2>&1
bench_rc=$?
tee -a "$log" < "$tmp"
rm -f "$tmp"
if [ $bench_rc -ne 0 ]; then
    echo "# bench.py failed (rc=$bench_rc) — skipping the ladder" | tee -a "$log"
    exit $bench_rc
fi
python benchmarks/ladder.py --full 2>&1 | tee -a "$log"
echo "# session log: $log"
