"""Beneš-routing feasibility study for the unstructured edge remainder.

BENCH.md's analysis says the hybrid method's floor is the gather for the
unstructured remainder (~8 cycles/element on the TPU, index-independent).
A Beneš network replaces the gather with ``2*log2(m) - 1`` columns of
2x2 switches; an XOR-butterfly Beneš column at distance ``d`` is

    y[i] = ctrl[i] ? x[i ^ d] : x[i]

— a reshape + reversed-slice + select, pure streaming VPU traffic with no
data-dependent addressing. Whether that beats the gather is a bandwidth
question, and the stage cost does NOT depend on the switch settings, so
phase 1 measures the stage structure with random controls (routing
correctness not required for timing):

    stage cost x (2 log2 m - 1)   vs   one m-element random gather

Phase 2 (only worth building if phase 1 wins): the looping algorithm to
compute real switch settings host-side, plus a copy-network phase for
multicast sources. Run: ``python benchmarks/benes.py [m_log2]``.
Prints one JSON line per measurement and a verdict line.
"""

import json
import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def benes_stages(k: int):
    """XOR distances of the 2k-1 Beneš columns (butterfly + inverse)."""
    return [2 ** j for j in range(k - 1, 0, -1)] + [2 ** j for j in range(k)]


def apply_stage(x, ctrl, d):
    """One switch column: y[i] = ctrl[i] ? x[i ^ d] : x[i]."""
    m = x.shape[0]
    xs = x.reshape(m // (2 * d), 2, d)
    swapped = xs[:, ::-1, :].reshape(m)
    return jnp.where(ctrl, swapped, x)


def make_network(k: int, key):
    """Random switch settings for every column (timing only)."""
    m = 2 ** k
    ds = benes_stages(k)
    ctrls = jax.random.bernoulli(key, 0.5, (len(ds), m))
    return ds, ctrls


def make_route(ds):
    @jax.jit
    def route(x, ctrls):
        for i, d in enumerate(ds):
            x = apply_stage(x, ctrls[i], d)
        return x

    return route


def timeit(fn, *args, reps=10):
    out = fn(*args)
    jax.block_until_ready(out)
    _ = np.asarray(out.ravel()[0])  # real sync (tunneled backend)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = np.asarray(out.ravel()[0])
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    k = int(sys.argv[1]) if len(sys.argv) > 1 else 21  # 2M wires
    m = 2 ** k
    key = jax.random.key(0)
    x = jax.random.normal(key, (m,), dtype=jnp.float32)
    perm = jax.random.permutation(jax.random.fold_in(key, 1), m)

    # Baseline: the gather the hybrid remainder currently pays.
    gather = jax.jit(lambda v, p: v[p])
    t_gather = timeit(gather, x, perm)
    emit = lambda r: print(json.dumps(r), flush=True)  # noqa: E731
    emit({"measure": "gather", "m": m, "ms": round(t_gather * 1e3, 3),
          "ns_per_elem": round(t_gather / m * 1e9, 3)})

    # Beneš stage structure with random controls.
    ds, ctrls = make_network(k, jax.random.fold_in(key, 2))
    routed = make_route(tuple(ds))
    t_benes = timeit(routed, x, ctrls)
    emit({"measure": "benes_stages", "m": m, "stages": len(ds),
          "ms": round(t_benes * 1e3, 3),
          "ns_per_elem_total": round(t_benes / m * 1e9, 3)})

    verdict = "benes_wins" if t_benes < t_gather else "gather_wins"
    emit({"measure": "verdict", "result": verdict,
          "speedup": round(t_gather / t_benes, 2),
          "note": ("switch-setting computation (phase 2) is only worth "
                   "building if benes_wins with margin; controls do not "
                   "affect stage cost")})
    return 0


if __name__ == "__main__":
    sys.exit(main())
