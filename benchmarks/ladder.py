"""The BASELINE.json scale ladder: every config, one JSON line each.

Configs (BASELINE.json "configs"):
  0. 3-node localhost broadcast over real sockets — the CPU reference
     anchor, the workload the reference's examples run
     [ref: examples/my_own_p2p_application.py].
  1. 1K-node Erdős–Rényi single-source flood, one chip.
  2. 100K-node Barabási–Albert push-pull gossip averaging.
  3. 1M-node Watts–Strogatz SIR rumor spread.
  4. 1M + (with --full) 10M-node Watts–Strogatz seen-set flood — the
     tx-flood config; the 10M graph specced for a v4-8 runs on ONE chip.

Run: ``python benchmarks/ladder.py [--full]``. The headline driver metric
stays in bench.py; this is the breadth harness.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()


def emit(record):
    print(json.dumps(record), flush=True)


def _sync(stats_entry):
    """Force device completion via a host transfer (block_until_ready can
    return early on tunneled backends)."""
    return float(stats_entry)


def bench_sockets_anchor():
    """Config 0: 3 real-socket nodes, timed broadcast delivery."""
    import threading

    from p2pnetwork_tpu import Node

    got = threading.Semaphore(0)

    class Counting(Node):
        def node_message(self, node, data):
            got.release()

    nodes = [Counting("127.0.0.1", 0, id=f"n{i}") for i in range(3)]
    try:
        for n in nodes:
            n.start()
        nodes[0].connect_with_node("127.0.0.1", nodes[1].port)
        nodes[1].connect_with_node("127.0.0.1", nodes[2].port)
        nodes[2].connect_with_node("127.0.0.1", nodes[0].port)
        deadline = time.monotonic() + 5
        while sum(len(n.all_nodes) for n in nodes) < 6 and time.monotonic() < deadline:
            time.sleep(0.01)
        n_msgs = 200
        t0 = time.perf_counter()
        for i in range(n_msgs):
            nodes[0].send_to_nodes(f"ping {i}")  # 2 deliveries each
        for _ in range(2 * n_msgs):
            got.acquire(timeout=10)
        secs = time.perf_counter() - t0
        emit({
            "config": "3-node localhost broadcast (sockets, CPU anchor)",
            "value": round(2 * n_msgs / secs, 1),
            "unit": "delivered msgs/s",
            "wall_s": round(secs, 4),
        })
    finally:
        for n in nodes:
            n.stop()
        for n in nodes:
            n.join(timeout=10)


def bench_flood_1k():
    import jax

    from p2pnetwork_tpu.models import Flood
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    g = G.erdos_renyi(1000, 0.01, seed=0)
    p = Flood(source=0, method="segment")
    key = jax.random.key(0)
    state, out = engine.run_until_coverage(g, p, key, coverage_target=0.99)
    _ = int(out["rounds"])  # warm
    t0 = time.perf_counter()
    state, out = engine.run_until_coverage(g, p, key, coverage_target=0.99)
    rounds = int(out["rounds"])
    secs = time.perf_counter() - t0
    emit({
        "config": "1K ER flood (single chip)",
        "value": round(secs * 1000, 3),
        "unit": "ms to 99% coverage",
        "rounds": rounds,
        "messages": int(out["messages"]),
    })


def bench_gossip_100k():
    import jax
    import numpy as np

    from p2pnetwork_tpu.models import Gossip
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    g = G.barabasi_albert(100_000, 4, seed=0, max_degree=128)
    p = Gossip(alpha=0.5)
    key = jax.random.key(0)
    rounds = 30
    state, stats = engine.run(g, p, key, rounds)
    _ = _sync(stats["variance"][-1])  # warm
    t0 = time.perf_counter()
    state, stats = engine.run(g, p, key, rounds)
    var_end = _sync(stats["variance"][-1])
    secs = time.perf_counter() - t0
    var = np.asarray(stats["variance"])
    emit({
        "config": "100K BA push-pull gossip (30 rounds)",
        "value": round(rounds * g.n_nodes / secs / 1e6, 1),
        "unit": "M node-updates/s",
        "wall_s": round(secs, 4),
        "variance_start": round(float(var[0]), 4),
        "variance_end": round(var_end, 6),
    })


def bench_sir_1m():
    import jax

    from p2pnetwork_tpu.models import SIR
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(1_000_000, 10, 0.1, seed=0, hybrid=True,
                         build_neighbor_table=False)
    p = SIR(beta=0.3, gamma=0.05, source=0, method="hybrid")
    key = jax.random.key(0)
    rounds = 30
    state, stats = engine.run(g, p, key, rounds)
    _ = _sync(stats["coverage"][-1])  # warm
    t0 = time.perf_counter()
    state, stats = engine.run(g, p, key, rounds)
    cov = _sync(stats["coverage"][-1])
    secs = time.perf_counter() - t0
    emit({
        "config": "1M WS SIR rumor spread (30 rounds)",
        "value": round(secs * 1000, 1),
        "unit": "ms",
        "coverage": round(cov, 4),
        "messages": int(sum(stats["messages"].tolist())),
        "msgs_per_s": round(float(sum(stats["messages"].tolist())) / secs / 1e6, 1),
    })


def bench_flood_big(n, label, adaptive_k=1024, *, make_graph=None,
                    method="hybrid", compare_methods=(), extra_fields=None):
    """Dense-vs-adaptive flood rung: one warm + one timed coverage run per
    protocol. ``make_graph`` swaps the topology (default 1M-family WS),
    ``method`` the dense lowering (``compare_methods`` adds rival dense
    lowerings — each is timed, the fastest drives the adaptive run and
    every time lands in the record), ``extra_fields(g)`` appends
    per-graph facts to the emitted record — one harness for every flood
    rung, so a timing-protocol fix lands on all of them at once."""
    import jax

    from p2pnetwork_tpu.models import AdaptiveFlood, Flood
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    t0 = time.perf_counter()
    if make_graph is None:
        g = G.watts_strogatz(n, 10, 0.1, seed=0, hybrid=True,
                             build_neighbor_table=False, source_csr=True)
    else:
        g = make_graph(G)
    build_s = time.perf_counter() - t0
    key = jax.random.key(0)

    def run(p):
        _, out = engine.run_until_coverage(g, p, key, coverage_target=0.99,
                                           max_rounds=64)
        _ = int(out["rounds"])  # warm
        t0 = time.perf_counter()
        _, out = engine.run_until_coverage(g, p, key, coverage_target=0.99,
                                           max_rounds=64)
        return time.perf_counter() - t0, out

    dense_times = {}
    for meth in (method, *compare_methods):
        dense_times[meth], _ = run(Flood(source=0, method=meth))
    method = min(dense_times, key=dense_times.get)
    dense_s = dense_times[method]
    secs, out = run(AdaptiveFlood(source=0, method=method, k=adaptive_k))
    emit({
        "config": label,
        "value": round(secs, 4),
        "unit": f"s to 99% coverage (adaptive-{adaptive_k}; "
                f"dense {method} {dense_s:.3f}s)",
        **({"dense_times_s": {m: round(s, 4)
                              for m, s in dense_times.items()}}
           if compare_methods else {}),
        "rounds": int(out["rounds"]),
        "messages": int(out["messages"]),
        "msgs_per_sec_per_chip": round(int(out["messages"]) / secs, 1),
        "graph_build_s": round(build_s, 1),
        **(extra_fields(g) if extra_fields else {}),
    })


def bench_flood_ba(n=100_000, m=4, adaptive_k=1024):
    """Seen-set flood on the scale-free (Barabási–Albert) family — the
    same 100K/m=4 edge topology as the BASELINE config-2 gossip rung
    (which additionally caps its gather TABLE at 128 — the edges and the
    hub degrees are identical), under the flood workload. Round 4's
    work-item chunking budgets sparse
    rounds by out-edge mass, so the hub-skewed degree distribution gets
    the adaptive win too (it was excluded before; VERDICT r3 #2).

    Dense lowerings raced per rung: sorted segment (the r4 answer —
    measured 0.118 s vs hybrid 0.41 s / pallas 2.17 s / padded gather
    3.97 s on this topology) vs the two-level skew table (ops/skew.py,
    VERDICT r4 #2) whose cost model predicts ~2x under segment."""
    bench_flood_big(
        n,
        f"{n//1_000_000}M BA (m={m}) seen-set flood, hub-tolerant "
        f"adaptive (single chip)" if n >= 1_000_000 else
        f"{n//1000}K BA (m={m}) seen-set flood, hub-tolerant adaptive "
        f"(single chip)",
        adaptive_k,
        make_graph=lambda G: G.barabasi_albert(
            n, m, seed=0, build_neighbor_table=False, source_csr=True,
            skew_table=True),
        method="segment",
        compare_methods=("skew",),
        extra_fields=lambda g: {"max_out_degree": max(1, g.max_out_span),
                                "skew_width": g.skew.width,
                                "skew_rows": g.skew.n_rows},
    )


def bench_flood_ba_1m(n=1_000_000, m=5, adaptive_k=2048):
    """The 1M-node scale-free rung (VERDICT r4 #2): ~10M directed edges
    under a power-law degree distribution — the realistic overlay shape
    at the north-star scale, where the hub machinery must prove itself
    end-to-end. Same recipe as the 100K rung, scaled."""
    bench_flood_ba(n, m, adaptive_k)


def bench_discovery(n=1_000_000, walkers=4096):
    """Peer-sampling discovery rung: how long a walker cohort takes to
    map 99% of a 1M-node overlay — the protocol family reference users
    hand-roll for crawling/peer sampling [ref: README.md:20], whole run
    device-side (models/walk.py RandomWalks + run_until_coverage)."""
    import jax

    from p2pnetwork_tpu.models import RandomWalks
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    t0 = time.perf_counter()
    g = G.watts_strogatz(n, 10, 0.1, seed=0, build_neighbor_table=False,
                         source_csr=True)
    build_s = time.perf_counter() - t0
    proto = RandomWalks(n_walkers=walkers)

    def once():
        _, out = engine.run_until_coverage(
            g, proto, jax.random.key(0), coverage_target=0.99,
            max_rounds=8192,
        )
        return out

    out = once()  # warm
    t0 = time.perf_counter()
    out = once()
    secs = time.perf_counter() - t0

    # The crawl is rounds-bound (~1700 rounds at a per-iteration floor set
    # by while_loop dispatch, not bandwidth): batching T walk rounds per
    # iteration (engine steps_per_round — bit-exact vs T=1, pinned by
    # tests/test_walk.py::TestBatchedSteps) amortizes that floor.
    def once_batched(T):
        _, o = engine.run_until_coverage(
            g, proto, jax.random.key(0), coverage_target=0.99,
            max_rounds=8192, steps_per_round=T,
        )
        return o

    best_T, best_secs, out_b = 1, secs, out
    for T in (8, 16, 32):
        ob = once_batched(T)  # warm (fresh compile per T)
        t0 = time.perf_counter()
        ob = once_batched(T)
        sb = time.perf_counter() - t0
        if sb < best_secs:
            best_T, best_secs, out_b = T, sb, ob
    assert out_b["rounds"] == out["rounds"], "batched walk not bit-exact"
    assert out_b["messages"] == out["messages"]

    emit({
        "config": f"{n//1_000_000}M WS overlay discovery, "
                  f"{walkers}-walker cohort (single chip)",
        "value": round(best_secs, 3),
        "unit": "s to 99% of the overlay visited",
        "steps_per_round": best_T,
        "unbatched_s": round(secs, 3),
        "rounds": int(out_b["rounds"]),
        "messages": int(out_b["messages"]),
        "rounds_per_s": round(int(out_b["rounds"]) / best_secs, 1),
        "graph_build_s": round(build_s, 1),
    })


def bench_plumtree(n=1_000_000):
    """Broadcast-tree rung: Plumtree's self-optimization contrast at 1M —
    the first broadcast floods every edge; the extracted tree
    (models/plumtree.py tree_graph) then carries repeated broadcasts at
    ~N messages. Emits the steady-state (extracted-tree) broadcast time."""
    import jax

    from p2pnetwork_tpu.models import Flood, Plumtree
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    t0 = time.perf_counter()
    g = G.watts_strogatz(n, 10, 0.1, seed=0, build_neighbor_table=False)
    build_s = time.perf_counter() - t0
    p = Plumtree(source=0)
    st = p.init(g, jax.random.key(0))
    st, stats0 = jax.jit(p.step)(g, st, jax.random.key(0))  # flood + prune
    flood_msgs = int(stats0["messages"])
    t0 = time.perf_counter()
    # The tree's max in-degree is 1: its neighbor table is one column
    # wide and the gather lowering is as cheap as aggregation gets.
    tg = p.tree_graph(g, st, source_csr=True)
    extract_s = time.perf_counter() - t0

    def once():
        _, out = engine.run_until_coverage(
            tg, Flood(source=0), jax.random.key(0), coverage_target=1.0,
            max_rounds=256)
        return out

    out = once()  # warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = once()
        times.append(time.perf_counter() - t0)
    emit({
        "config": f"{n//1_000_000}M WS Plumtree broadcast tree "
                  f"(single chip)",
        "value": round(min(times), 3),
        "unit": "s per steady-state broadcast over the extracted tree",
        "rounds": int(out["rounds"]),
        "messages": int(out["messages"]),
        "flood_messages": flood_msgs,
        "message_reduction": round(flood_msgs / int(out["messages"]), 1),
        "extract_s": round(extract_s, 1),
        "graph_build_s": round(build_s, 1),
    })


def bench_routing(n=1_000_000):
    """Weighted routing rung: latency-weighted distance-vector tables
    for the whole overlay (models/routing.py DistanceVector — one
    propagate_min_plus per round, run-to-quiescence device-side), the
    RIP-style protocol reference users hand-roll on node_message."""
    import jax
    import numpy as np

    from p2pnetwork_tpu.models import DistanceVector
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    t0 = time.perf_counter()
    g = G.watts_strogatz(n, 10, 0.1, seed=0, build_neighbor_table=False)

    def latency(s, r):
        h = (s.astype(np.uint32) * np.uint32(2654435761)
             + r.astype(np.uint32))
        return 1.0 + (h % 2048).astype(np.float32) / 1024.0

    g = g.with_weights(latency)
    build_s = time.perf_counter() - t0

    def once():
        _, out = engine.run_until_converged(
            g, DistanceVector(source=0, method="segment"),
            jax.random.key(0), stat="changed", threshold=1, max_rounds=256,
        )
        return out

    out = once()  # warm
    t0 = time.perf_counter()
    out = once()
    secs = time.perf_counter() - t0
    emit({
        "config": f"{n:,}-node WS weighted distance-vector routing "
                  f"(single chip)",
        "value": round(secs, 3),
        "unit": "s to converged cost + next-hop tables",
        "rounds": int(out["rounds"]),
        "messages": int(out["messages"]),
        "graph_build_s": round(build_s, 1),
    })


def bench_flood_auto():
    """GSPMD auto path (parallel/auto.py) on every available device, both
    lowerings: the segment-method flood (the idiom's historical floor,
    paying the full scatter cost) and the hybrid-blocked method (diagonal
    rolls + einsum remainder — every op partitionable), which closes the
    gap to the explicit ring path. On one chip this measures the
    unpartitioned programs; multi-device communication is bounded
    node-extent by HLO inspection (tests/test_auto_comm.py), which no
    single-chip wall-clock can show."""
    import jax

    from p2pnetwork_tpu.models import Flood
    from p2pnetwork_tpu.parallel import auto
    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.sim import graph as G

    mesh = M.ring_mesh()
    g = auto.shard_graph_auto(
        G.watts_strogatz(1_000_000, 10, 0.1, seed=0,
                         build_neighbor_table=False, hybrid=True),
        mesh,
    )
    key = jax.random.key(0)
    for method in ("segment", "hybrid-blocked"):
        p = Flood(source=0, method=method)
        _, out = engine.run_until_coverage(g, p, key, coverage_target=0.99,
                                           max_rounds=64)
        _ = int(out["rounds"])  # warm
        t0 = time.perf_counter()
        _, out = engine.run_until_coverage(g, p, key, coverage_target=0.99,
                                           max_rounds=64)
        secs = time.perf_counter() - t0
        emit({
            "config": f"1M WS flood, GSPMD auto ({mesh.devices.size} dev, "
                      f"{method} lowering)",
            "value": round(secs, 4),
            "unit": "s to 99% coverage (compiler-placed collectives)",
            "rounds": int(out["rounds"]),
            "messages": int(out["messages"]),
            "comm_evidence": "tests/test_auto_comm.py pins collectives to "
                             "node-extent payloads on the 8-device mesh",
        })


def bench_gossip_sharded():
    """Sharded (ring ppermute) gossip on every available device — the
    multi-chip path of configs[2]; on one chip this measures the S=1 ring
    overhead vs the single-device entry above."""
    import jax

    from p2pnetwork_tpu.models import Gossip
    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.parallel import sharded
    from p2pnetwork_tpu.sim import graph as G

    n_dev = len(jax.devices())
    mesh = M.ring_mesh(n_dev)
    g = G.barabasi_albert(100_000, 4, seed=0, max_degree=128)
    sg = sharded.shard_graph(g, mesh)
    p = Gossip(alpha=0.5)
    key = jax.random.key(0)
    rounds = 30
    vals, stats = sharded.gossip(sg, mesh, p, key, rounds)
    _ = _sync(stats["variance"][-1])  # warm
    t0 = time.perf_counter()
    vals, stats = sharded.gossip(sg, mesh, p, key, rounds)
    var_end = _sync(stats["variance"][-1])
    secs = time.perf_counter() - t0
    emit({
        "config": f"100K BA push-pull gossip, sharded ring ({n_dev} dev, 30 rounds)",
        "value": round(rounds * g.n_nodes / secs / 1e6, 1),
        "unit": "M node-updates/s",
        "wall_s": round(secs, 4),
        "variance_end": round(var_end, 6),
    })


def bench_flood_sharded_ring():
    """1M flood-to-99% on the explicit ring path (every available device;
    one chip measures ring overhead vs the single-chip hybrid entry) —
    segment reductions vs the MXU bucket layout."""
    import numpy as np

    from p2pnetwork_tpu.parallel import mesh as M
    from p2pnetwork_tpu.parallel import sharded
    from p2pnetwork_tpu.sim import graph as G

    mesh = M.ring_mesh()
    g = G.watts_strogatz(1_000_000, 10, 0.1, seed=0,
                         build_neighbor_table=False)
    results = {}
    for label, kw, call_kw in (
        ("segment", {}, {}),
        ("mxu", dict(mxu=True), {}),
        ("hybrid", dict(hybrid=True), {}),
        ("adaptive", dict(hybrid=True, source_csr=True),
         dict(adaptive_k=1024)),
    ):
        sg = sharded.shard_graph(g, mesh, **kw)
        seen, out = sharded.flood_until_coverage(sg, mesh, source=0,
                                                 **call_kw)  # warm
        t0 = time.perf_counter()
        seen, out = sharded.flood_until_coverage(sg, mesh, source=0,
                                                 **call_kw)
        _ = out["messages"]  # blocking summary transfer
        results[label] = time.perf_counter() - t0
    emit({
        "config": f"1M WS flood, ring-sharded ({mesh.devices.size} dev)",
        "value": round(results["adaptive"], 4),
        "unit": "s to 99% coverage (hybrid layout + frontier-adaptive "
                "rounds)",
        "segment_s": round(results["segment"], 4),
        "mxu_s": round(results["mxu"], 4),
        "hybrid_s": round(results["hybrid"], 4),
        "adaptive_speedup_vs_segment": round(
            results["segment"] / results["adaptive"], 2
        ),
        "rounds": int(np.asarray(out["rounds"])),
    })


def bench_churn_connect():
    """Runtime connect cost vs graph size: the membership probe is a
    searchsorted window scan (sim/topology.py), so a connect batch should
    cost about the same at 100K and at 1M nodes — not 10x more."""
    import jax

    from p2pnetwork_tpu.sim import graph as G
    from p2pnetwork_tpu.sim import topology

    batch = 64
    rng_s = [(i * 37) % 99_000 for i in range(batch)]
    rng_r = [(i * 91 + 13) % 99_000 for i in range(batch)]
    times = {}
    for n in (100_000, 1_000_000):
        g = G.watts_strogatz(n, 10, 0.1, seed=0, build_neighbor_table=False)
        g = topology.with_capacity(g, extra_edges=4 * batch)
        s = jax.numpy.asarray(rng_s, jax.numpy.int32)
        r = jax.numpy.asarray(rng_r, jax.numpy.int32)
        g2 = topology.connect(g, s, r, check_capacity=False)
        jax.block_until_ready(g2.dyn_mask)  # warm (compile)
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            g2 = topology.connect(g, s, r, check_capacity=False)
            jax.block_until_ready(g2.dyn_mask)
        times[n] = (time.perf_counter() - t0) / reps
    emit({
        "config": f"runtime connect, {batch}-link batch (no capacity sync)",
        "value": round(times[1_000_000] * 1e3, 3),
        "unit": "ms/batch at 1M nodes (10M edges)",
        "ms_at_100k": round(times[100_000] * 1e3, 3),
        "scaling_1m_over_100k": round(times[1_000_000] / times[100_000], 2),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="include the 10M-node config (long graph build)")
    args = ap.parse_args()

    bench_sockets_anchor()
    bench_flood_1k()
    bench_gossip_100k()
    bench_gossip_sharded()
    bench_sir_1m()
    bench_churn_connect()
    bench_flood_sharded_ring()
    bench_flood_auto()
    bench_flood_ba()
    bench_flood_ba_1m()
    bench_discovery()
    bench_plumtree()
    bench_routing()
    bench_flood_big(1_000_000, "1M WS seen-set flood (single chip)")
    if args.full:
        bench_flood_big(10_000_000, "10M WS seen-set flood (single chip)",
                        adaptive_k=2048)
        bench_plumtree(10_000_000)
    return 0


if __name__ == "__main__":
    sys.exit(main())
