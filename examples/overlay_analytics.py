"""Sim backend demo: overlay-health analytics as compiled protocols.

Ten questions reference users answer by hand-instrumenting callbacks
[ref: README.md:20] — who matters (PageRank), how far is everyone
(HopDistance / BFS), what's the network-wide average (PushSum), who
coordinates (LeaderElection), is the network partitioned and how badly
(ConnectedComponents, after node failures), can peers be 2-colored into
roles (BipartiteCheck), how clustered is the overlay
(transitivity_sample), which peers form the resilient core (KCore),
which peers the shortest paths route through (betweenness_sample), and
which peers are nearest to everyone (closeness_sample) — each runs here
as a batched protocol over the whole population in one compiled scan
(clustering and the centralities as one-shot device queries).

Every run-to-* loop also reports through the unified telemetry plane
(p2pnetwork_tpu/telemetry): the closing section reads the registry
SNAPSHOT — rounds/messages/wall-time per loop kind, injected failures,
jit compile wall time — the same numbers a live deployment would scrape
from the Prometheus endpoint (telemetry.MetricsServer).
Run: ``python examples/overlay_analytics.py`` (CPU ok; TPU if available).
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.models import (BipartiteCheck, ConnectedComponents,
                                   HopDistance, KCore, LeaderElection,
                                   PageRank, PushSum, betweenness_sample,
                                   closeness_sample, transitivity_sample)
from p2pnetwork_tpu.sim import engine, failures
from p2pnetwork_tpu.sim import graph as G


def main():
    n = 50_000
    print(f"building {n}-node Barabasi-Albert overlay ...")
    g = G.barabasi_albert(n, 4, seed=0)
    print(f"  {g.n_edges} directed edges")

    # Who matters: PageRank power iteration to a tight residual.
    t0 = time.perf_counter()
    state, stats = engine.run(g, PageRank(damping=0.85), jax.random.key(0), 40)
    ranks = np.asarray(state.ranks)[:n]
    dt = time.perf_counter() - t0
    top = np.argsort(ranks)[::-1][:5]
    print(f"PageRank (40 rounds, {dt*1000:.0f} ms incl. compile): "
          f"residual {float(np.asarray(stats['residual'])[-1]):.2e}")
    print("  top-5 hubs:", ", ".join(f"node {i} ({ranks[i]:.2e})" for i in top))

    # How far is everyone: BFS hop layers from node 0.
    state, out = engine.run_until_coverage(
        g, HopDistance(source=0), jax.random.key(0), coverage_target=1.0,
        max_rounds=64,
    )
    dist = np.asarray(state.dist)[:n]
    reached = dist >= 0
    print(f"HopDistance: {int(out['rounds'])} rounds, "
          f"{reached.mean()*100:.1f}% reachable, "
          f"eccentricity {dist.max()}, mean hops {dist[reached].mean():.2f}")

    # What's the average: push-sum consensus (every node converges on the
    # network-wide mean with no coordinator).
    proto = PushSum()
    st0 = proto.init(g, jax.random.key(1))
    true_mean = float(np.asarray(st0.s)[:n].mean())
    state, stats = engine.run(g, proto, jax.random.key(1), 60)
    est = np.asarray(proto.estimate(g, state))[:n]
    print(f"PushSum: true mean {true_mean:+.5f}, "
          f"estimates [{est.min():+.5f}, {est.max():+.5f}] after 60 rounds "
          f"(variance {float(np.asarray(stats['variance'])[-1]):.2e})")

    # Who coordinates: highest-live-id election, run until silent.
    state, out = engine.run_until_converged(
        g, LeaderElection(), jax.random.key(2), stat="changed", threshold=1,
        max_rounds=128,
    )
    known = np.asarray(state.known)[:n]
    leader = int(known.max())
    agree = float((known == leader).mean())
    print(f"LeaderElection: node {leader} elected by {agree:.1%} of peers "
          f"in {int(out['rounds'])} rounds ({int(out['messages'])} messages)")

    # Is the overlay partitioned: knock out the top hubs, then count the
    # surviving components by max-label flooding.
    top_hubs = [int(i) for i in np.argsort(ranks)[::-1][:50]]
    gf = failures.fail_nodes(g, top_hubs)
    proto = ConnectedComponents()
    state, out = engine.run_until_converged(
        gf, proto, jax.random.key(3), stat="changed", threshold=1,
        max_rounds=256,
    )
    parts = int(proto.components(gf, state))
    print(f"ConnectedComponents: after failing the top-50 hubs the overlay "
          f"splits into {parts} partition(s) "
          f"({int(out['rounds'])} rounds to quiesce)")

    # Can peers be split into two roles with links only across the split
    # (request/response, storage/index): odd-cycle detection by the same
    # max-label flood, recording BFS layers as it goes.
    proto = BipartiteCheck()
    state, out = engine.run_until_converged(
        g, proto, jax.random.key(5), stat="changed", threshold=1,
        max_rounds=256,
    )
    odd = int(proto.odd_edges(g, state))
    verdict = "bipartite" if odd == 0 else f"not bipartite ({odd} odd edge slots)"
    print(f"BipartiteCheck: the overlay is {verdict} "
          f"({int(out['rounds'])} rounds to quiesce)")

    # How clustered is the overlay: unbiased wedge sampling (the BA hubs
    # make the exact [B, d, d] intersection path quadratic in hub degree;
    # the sampler is degree-free).
    gcsr = g.with_source_csr()
    t_est = transitivity_sample(gcsr, jax.random.key(6), 1 << 16)
    print(f"transitivity_sample: global clustering ~ {t_est:.4f} "
          f"(65536 wedges)")

    # Who forms the resilient core: recursive peeling of under-connected
    # peers (the k-core) on the intact overlay.
    state, out = engine.run_until_converged(
        g, KCore(k=4), jax.random.key(4), stat="removed", threshold=1,
        max_rounds=256,
    )
    core = int(np.asarray(state.in_core).sum())
    print(f"KCore k=4: {core}/{n} peers survive recursive peeling "
          f"({int(out['rounds'])} rounds)")

    # Which peers the traffic actually routes through: sampled Brandes
    # betweenness (64 sources -> unbiased estimate of the full sum).
    src = jax.random.choice(jax.random.key(8), n, (64,), replace=False)
    bc = np.asarray(betweenness_sample(g, src, normalized=True))
    top_bc = np.argsort(bc)[-5:][::-1]
    print("betweenness (sampled): top-5 relays:",
          ", ".join(f"node {i} ({bc[i]:.0f})" for i in top_bc))

    # And which peers are NEAREST to everyone (placement, not relaying):
    # harmonic closeness over the same sampled sources.
    cc = np.asarray(closeness_sample(g, src, normalized=True))
    top_cc = np.argsort(cc)[-5:][::-1]
    print("closeness (sampled): top-5 best-placed:",
          ", ".join(f"node {i} ({cc[i]:.0f})" for i in top_cc))

    # What did all of that cost? The registry snapshot is the in-process
    # face of the telemetry plane (the Prometheus endpoint serves the same
    # families to a scraper — see GETTING_STARTED.md "Observability").
    snap = telemetry.default_registry().snapshot()
    print("\ntelemetry snapshot:")
    for fam in ("sim_runs_total", "sim_rounds_total", "sim_messages_total"):
        for s in snap.get(fam, {}).get("samples", []):
            print(f"  {fam}{s['labels']}: {s['value']:.0f}")
    for s in snap.get("sim_injected_failures_total", {}).get("samples", []):
        print(f"  sim_injected_failures_total{s['labels']}: {s['value']:.0f}")
    for s in snap.get("sim_run_seconds", {}).get("samples", []):
        print(f"  sim_run_seconds{s['labels']}: "
              f"count={s['count']} sum={s['sum']:.3f}s")
    compile_s = telemetry.default_registry().value(
        "jax_compile_seconds_total", stage="backend_compile")
    print(f"  jax backend-compile wall: {compile_s:.2f}s")


if __name__ == "__main__":
    main()
