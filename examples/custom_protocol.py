"""Write your own protocol — single-device AND multi-chip, no library changes.

The reference deliberately ships no protocol: users implement flooding /
discovery / aggregation themselves in ``node_message`` overrides
[ref: README.md:20]. This framework keeps that identity at TPU scale. A
protocol here is two pure jittable functions behind the models/base.py
seam; this example builds one the library does NOT ship — **decaying
heat diffusion** (each node keeps half its heat and spreads the rest
equally over its out-edges; heat injected at one node, total heat
conserved) — and runs it two ways:

1. against the single-device engine (``engine.run``), like any shipped
   protocol;
2. as a round function written around :func:`sharded.propagate` — the
   generic one-pass edge aggregation of the ring path — jitted over an
   8-device mesh, with results parity-checked against (1).

Run: ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/custom_protocol.py`` (or on real chips unchanged).
"""

import dataclasses
import sys

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.ops import segment
from p2pnetwork_tpu.parallel import mesh as M
from p2pnetwork_tpu.parallel import sharded
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G


# ----------------------------------------------------- the custom protocol


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HeatState:
    heat: jax.Array  # f32[N_pad]


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class HeatDiffusion:
    """Keep ``retain`` of your heat, spread the rest over out-edges."""

    source: int = 0
    retain: float = 0.5

    def init(self, graph, key):
        heat = jnp.zeros(graph.n_nodes_padded, dtype=jnp.float32)
        return HeatState(heat=heat.at[self.source].set(1.0))

    def step(self, graph, state, key):
        deg = graph.out_degree.astype(jnp.float32)
        spread = jnp.where(deg > 0, (1.0 - self.retain) / jnp.maximum(deg, 1.0),
                           0.0)
        kept = jnp.where(deg > 0, self.retain, 1.0) * state.heat
        heat = kept + segment.propagate_sum(graph, state.heat * spread)
        stats = {
            "messages": segment.frontier_messages(graph, state.heat > 0),
            "heat_total": jnp.sum(heat),
            "heat_max": jnp.max(heat),
        }
        return HeatState(heat=heat), stats


def main():
    n = 8192
    g = G.watts_strogatz(n, 6, 0.1, seed=0)
    rounds = 20
    proto = HeatDiffusion(source=7)

    # 1) Single-device engine — the protocol seam, like any shipped model.
    state, stats = engine.run(g, proto, jax.random.key(0), rounds)
    heat_ref = np.asarray(state.heat)[:n]
    print(f"engine: total heat {float(np.asarray(stats['heat_total'])[-1]):.6f} "
          f"(conserved), hottest node {heat_ref.argmax()} "
          f"({heat_ref.max():.4f})")

    # 2) Multi-chip: the same round, written around sharded.propagate.
    mesh = M.ring_mesh(min(8, len(jax.devices())))
    sg = sharded.shard_graph(g, mesh)
    S, block = sg.n_shards, sg.block
    deg = sg.out_degree.astype(jnp.float32)
    spread = jnp.where(deg > 0, (1.0 - proto.retain) / jnp.maximum(deg, 1.0),
                       0.0)
    keep = jnp.where(deg > 0, proto.retain, 1.0)

    heat = jnp.zeros((S, block), jnp.float32).at[
        proto.source // block, proto.source % block].set(1.0)
    for _ in range(rounds):
        heat = keep * heat + sharded.propagate(sg, mesh, heat * spread,
                                               op="sum")
    heat_sh = np.asarray(heat).reshape(-1)[:n]

    err = np.abs(heat_sh - heat_ref).max()
    assert err < 1e-6, f"sharded diverged from engine: {err}"
    print(f"sharded ({S} devices): bit-compatible with the engine "
          f"(max |diff| {err:.2e}) — same protocol, zero library changes")


if __name__ == "__main__":
    main()
