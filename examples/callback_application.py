"""Callback-style extension demo (sockets backend).

The reference's alternative plugin style [ref: examples/
my_own_p2p_application_callback.py]: instead of subclassing, pass a
``callback(event, main_node, connected_node, data)``.
Run: ``python examples/callback_application.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node


def node_callback(event, main_node, connected_node, data):
    peer = getattr(connected_node, "id", "?")
    if event == "node_message":
        print(f"  [{main_node.id}] {event} from {peer}: {data!r}")
    else:
        print(f"  [{main_node.id}] {event} ({peer})")


def main():
    alice = Node("127.0.0.1", 0, id="alice", callback=node_callback)
    bob = Node("127.0.0.1", 0, id="bob", callback=node_callback)
    alice.start()
    bob.start()

    alice.connect_with_node("127.0.0.1", bob.port)
    time.sleep(0.3)
    alice.send_to_nodes("hello bob")
    bob.send_to_nodes({"reply": "hello alice"})
    time.sleep(0.3)

    # The structured event log records the same history for inspection.
    print("bob's event log:", [e.event for e in bob.event_log.snapshot()])

    for n in (alice, bob):
        n.stop()
    for n in (alice, bob):
        n.join()


if __name__ == "__main__":
    main()
