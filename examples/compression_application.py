"""Compression demo (sockets backend).

The capability shown in the reference's
examples/my_own_p2p_application_compression.py:37-40 — large payloads
broadcast with each supported codec (zlib, bzip2, lzma) plus a compressed
dict, the receiver decompressing transparently off the algorithm tag baked
into the wire format [ref: p2pnetwork/nodeconnection.py:63-70].
Run: ``python examples/compression_application.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node


class ReceiverNode(Node):
    """Counts what arrives; payloads land already decompressed."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.received = []

    def node_message(self, node, data):
        kind = type(data).__name__
        size = len(data) if hasattr(data, "__len__") else 1
        print(f"  [{self.id}] received {kind} ({size} chars/keys)")
        self.received.append(data)
        super().node_message(node, data)


def main():
    sender = Node("127.0.0.1", 0, id="sender")
    receiver = ReceiverNode("127.0.0.1", 0, id="receiver")
    sender.start()
    receiver.start()
    sender.connect_with_node("127.0.0.1", receiver.port)
    time.sleep(0.2)

    # A highly compressible payload: 400 repeated chars shrinks to a few
    # dozen wire bytes under any of the three codecs.
    payload = "a" * 400
    for codec in ("zlib", "bzip2", "lzma"):
        print(f"broadcast with {codec}:")
        sender.send_to_nodes(payload, compression=codec)
        time.sleep(0.2)

    print("compressed dict broadcast:")
    sender.send_to_nodes({"key": "value", "key2": "value2"}, compression="zlib")
    time.sleep(0.3)

    ok = (
        len(receiver.received) == 4
        and all(p == payload for p in receiver.received[:3])
        and receiver.received[3] == {"key": "value", "key2": "value2"}
    )
    print(f"received {len(receiver.received)}/4 payloads intact: {ok}")
    for n in (sender, receiver):
        n.stop()
    for n in (sender, receiver):
        n.join()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
