"""Sockets backend + live Prometheus endpoint, end to end in ~5 seconds.

Two real TCP nodes exchange traffic (one of them a PhiAccrualNode heart-
beating), a stdlib MetricsServer exposes the process registry, and the
script then SCRAPES its own endpoint over HTTP — asserting the text
exposition carries the sockets metric families a real deployment would
chart: per-node message counters, per-peer byte counters, the handle-
latency histogram, connection gauges, and phi suspicion. Finally the
shared JSONL stream (metric samples + EventLog events, one schema) is
written and counted. This is the demo `make telemetry-check` runs.

Run: ``python examples/telemetry_demo.py`` (no jax required).
"""

import io
import json
import sys
import time
import urllib.request

sys.path.insert(0, ".")

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.node import Node
from p2pnetwork_tpu.phi import PhiAccrualNode


def main():
    a = PhiAccrualNode("127.0.0.1", 0, id="alice")
    b = Node("127.0.0.1", 0, id="bob")
    a.start()
    b.start()
    a.connect_with_node("127.0.0.1", b.port)

    with telemetry.MetricsServer(port=0) as srv:
        print(f"metrics live at {srv.url}  (curl it while this runs)")
        deadline = time.monotonic() + 3.0
        i = 0
        while time.monotonic() < deadline:
            a.send_to_nodes({"seq": i})
            b.send_to_nodes({"ack": i}, compression="zlib")
            a.tick()  # heartbeat -> phi estimator
            i += 1
            time.sleep(0.05)
        a.suspicion_levels()  # refresh the phi gauge before the scrape

        body = urllib.request.urlopen(srv.url, timeout=5).read().decode()

    wanted = [
        "p2p_messages_sent_total", "p2p_messages_received_total",
        "p2p_bytes_sent_total", "p2p_bytes_received_total",
        "p2p_message_handle_seconds_bucket", "p2p_connections",
        "p2p_events_total", "p2p_heartbeats_received_total",
    ]
    missing = [w for w in wanted if w not in body]
    assert not missing, f"scrape missing families: {missing}"
    shown = [ln for ln in body.splitlines()
             if ln.startswith(("p2p_messages", "p2p_bytes", "p2p_connections"))]
    print("\n".join(shown))

    # One stream, one schema: metric samples and socket events interleave.
    buf = io.StringIO()
    n_metrics = telemetry.write_jsonl(sink=buf)
    n_events = a.event_log.to_jsonl(buf)
    kinds = {json.loads(ln)["type"] for ln in buf.getvalue().splitlines()}
    print(f"jsonl stream: {n_metrics} metric samples + {n_events} events, "
          f"record types {sorted(kinds)}")
    assert "event" in kinds and "counter" in kinds

    for n in (a, b):
        n.stop()
    for n in (a, b):
        n.join(timeout=10)
    print("telemetry demo OK")


if __name__ == "__main__":
    main()
