"""Sim backend demo: the weighted stack — latency embedding, cheapest
backbone, and routing over one overlay.

Three questions a latency-aware deployment asks of the same weighted
graph (link costs as `edge_weight`), each answered by a batched
protocol:

1. *Where is everyone?* — Vivaldi springs per-node coordinates from
   sampled link latencies until coordinate distance predicts RTT
   (models/vivaldi.py);
2. *What is the cheapest backbone connecting all peers?* — Borůvka
   merges fragments along minimum-weight links into the MSF
   (models/boruvka.py), and the backbone's total cost is compared
   against a naive BFS tree's;
3. *How does traffic flow?* — DistanceVector converges exact
   latency-weighted next-hop tables (models/routing.py), and routing
   stretch over the backbone alone shows what the redundant links buy.

Run: ``python examples/weighted_backbone.py`` (CPU ok; TPU if available).
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from p2pnetwork_tpu.models import Boruvka, DistanceVector, Vivaldi
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G


def main():
    side = 100
    n = side * side
    print(f"building a latency-weighted {n}-node overlay ...")
    # Peers on a 2-D latency map (a 100x100 grid): links to the nearby
    # peers plus a few random far contacts each — the mix latency-aware
    # overlays actually run (and the mix Vivaldi needs: without springs
    # at every distance scale the embedding can satisfy local links
    # while globally folded). Weights = ground distance + a 1.0 floor,
    # as real RTTs have.
    rng = np.random.default_rng(0)
    xs = np.array([(i % side, i // side) for i in range(n)], np.float32)
    base = np.arange(n, dtype=np.int64)
    local = [(base, (base + d) % n)
             for d in (1, side, side + 1, side - 1)]
    far = [(base, rng.permutation(n)) for _ in range(3)]
    s_all = np.concatenate([p[0] for p in local + far])
    r_all = np.concatenate([p[1] for p in local + far])
    keep = s_all != r_all
    pairs = {(min(int(a), int(b)), max(int(a), int(b)))
             for a, b in zip(s_all[keep], r_all[keep])}
    lo = np.array([p[0] for p in pairs], np.int32)
    hi = np.array([p[1] for p in pairs], np.int32)
    xj = jnp.asarray(xs)
    g = G.from_edges(np.concatenate([lo, hi]), np.concatenate([hi, lo]),
                     n).with_weights(
        lambda s, r: 1.0 + jnp.sqrt(jnp.sum((xj[s] - xj[r]) ** 2, axis=-1)))

    # 1. Vivaldi: spring until sampled rmse stabilizes.
    t0 = time.perf_counter()
    proto = Vivaldi(dim=2)
    st, out = engine.run(g, proto, jax.random.key(0), 1500)
    rmse = float(np.asarray(out["rmse"])[-1])
    i = rng.integers(0, n, 512)
    j = rng.integers(0, n, 512)
    keep = i != j
    pred = np.asarray(proto.predicted(st, jnp.asarray(i[keep]),
                                      jnp.asarray(j[keep])))
    # True RTT mirrors the link model exactly: distance + the 1.0 floor
    # (which the pair's two learned heights absorb between them).
    true = np.linalg.norm(xs[i[keep]] - xs[j[keep]], axis=1) + 1.0
    err = np.median(np.abs(pred - true) / true)
    print(f"Vivaldi: sampled-spring rmse {rmse:.2f} after 1500 rounds "
          f"({time.perf_counter()-t0:.1f}s); median relative error on "
          f"unsampled pairs {err:.3%}")

    # 2. Boruvka: the cheapest connecting backbone.
    st_b, out_b = engine.run_until_converged(
        g, Boruvka(), jax.random.key(0), stat="changed", threshold=1,
        max_rounds=64)
    msf_w = float(st_b.mst_weight)
    n_edges = int(np.asarray(st_b.mst_edge).sum())
    # Contrast: an unweighted BFS tree's total latency.
    from p2pnetwork_tpu.models import SpanningTree
    st_t, _ = engine.run_until_coverage(
        g, SpanningTree(source=0), jax.random.key(0), coverage_target=1.0)
    parent = np.asarray(st_t.parent)
    w_lookup = {}
    s_np = np.asarray(g.senders)
    r_np = np.asarray(g.receivers)
    w_np = np.asarray(g.edge_weight)
    em = np.asarray(g.edge_mask)
    for a, b, w in zip(s_np[em], r_np[em], w_np[em]):
        w_lookup[(int(a), int(b))] = float(w)
    bfs_w = sum(w_lookup[(int(parent[v]), v)]
                for v in range(n) if parent[v] >= 0 and parent[v] != v)
    print(f"Boruvka MSF: {n_edges} links, total latency {msf_w:,.0f} "
          f"in {int(out_b['rounds'])} phases — vs {bfs_w:,.0f} for the "
          f"hop-count BFS tree ({bfs_w/msf_w:.2f}x heavier)")

    # 3. Exact weighted routing tables, full graph vs backbone-only.
    src = 0
    p_dv = DistanceVector(source=src)
    st_full, out_f = engine.run_until_converged(
        g, p_dv, jax.random.key(0), stat="changed", threshold=1,
        max_rounds=512)
    # The MSF marks ONE directed slot per undirected tree edge —
    # symmetrize so routing can traverse the backbone both ways.
    mst = np.asarray(st_b.mst_edge)
    bb_s = np.concatenate([s_np[mst], r_np[mst]])
    bb_r = np.concatenate([r_np[mst], s_np[mst]])
    bb_w = np.concatenate([w_np[mst], w_np[mst]])
    g_bb = G.from_edges(bb_s, bb_r, n, weights=bb_w,
                        node_pad_multiple=g.n_nodes_padded)
    st_bb, _ = engine.run_until_converged(
        g_bb, p_dv, jax.random.key(0), stat="changed", threshold=1,
        max_rounds=2048)
    d_full = np.asarray(st_full.dist)[:n]
    d_bb = np.asarray(st_bb.dist)[:n]
    ok = np.isfinite(d_full) & (d_full > 0)
    stretch = float(np.median(d_bb[ok] / d_full[ok]))
    print(f"DistanceVector: converged in {int(out_f['rounds'])} rounds; "
          f"median backbone-only routing stretch {stretch:.2f}x — the "
          f"price of dropping every non-backbone link")


if __name__ == "__main__":
    main()
