"""A standalone, importable Node subclass — the documented way to
structure an application on this framework.

The reference ships the same pattern as its own module
[ref: examples/MyOwnPeer2PeerNode.py:7-34, described in
examples/README.md]: put your protocol class in one file, import it from
your application scripts. Every event hook of the Extension API
[ref: p2pnetwork/node.py:282-363] is overridden here so you can see the
full vocabulary in one place; delete the ones you don't need.

Use it from an application script::

    from examples.my_peer2peer_node import MyPeer2PeerNode

    node = MyPeer2PeerNode("127.0.0.1", 0)
    node.start()
"""

import sys

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node


class MyPeer2PeerNode(Node):
    """Your protocol lives in these hooks; each falls through to the base
    implementation so the callback channel and event log keep working."""

    def __init__(self, host, port, id=None):
        super().__init__(host, port, id)
        print(f"MyPeer2PeerNode: started on {self.host}:{self.port}")

    def outbound_node_connected(self, node):
        print(f"[{self.id[:8]}] connected to peer {node.id[:8]}")
        super().outbound_node_connected(node)

    def inbound_node_connected(self, node):
        print(f"[{self.id[:8]}] peer {node.id[:8]} connected to us")
        super().inbound_node_connected(node)

    def inbound_node_disconnected(self, node):
        print(f"[{self.id[:8]}] inbound peer {node.id[:8]} left")
        super().inbound_node_disconnected(node)

    def outbound_node_disconnected(self, node):
        print(f"[{self.id[:8]}] outbound peer {node.id[:8]} left")
        super().outbound_node_disconnected(node)

    def node_message(self, node, data):
        print(f"[{self.id[:8]}] message from {node.id[:8]}: {data!r}")
        super().node_message(node, data)

    def node_disconnect_with_outbound_node(self, node):
        print(f"[{self.id[:8]}] disconnecting from {node.id[:8]}")
        super().node_disconnect_with_outbound_node(node)

    def node_request_to_stop(self):
        print(f"[{self.id[:8]}] stop requested")
        super().node_request_to_stop()
