"""Sim backend demo: overlay discovery with a random-walk cohort.

The discovery/peer-sampling protocol the reference tells users to write
in ``node_message`` [ref: README.md:20, GETTING_STARTED.md:9]: a crawler
cohort walks the overlay, and coverage of the visited set answers "how
much of the network have we mapped?". Here the whole cohort advances in
one batched step per round, the run-to-coverage loop executes device-side,
and a runtime bridge (connect) plus churn (failures) happen mid-crawl
with no graph rebuild.
Run: ``python examples/discovery_walk_demo.py`` (CPU ok; TPU if available).
"""

import sys

sys.path.insert(0, ".")

import jax
import numpy as np

from p2pnetwork_tpu.models import RandomWalks
from p2pnetwork_tpu.sim import engine, failures, topology
from p2pnetwork_tpu.sim import graph as G


def main():
    n = 20_000
    g = G.watts_strogatz(n, 8, 0.2, seed=0, source_csr=True)
    g = topology.with_capacity(g, extra_edges=32)
    proto = RandomWalks(n_walkers=256, restart_p=0.02)
    print(f"{n}-node overlay, {proto.n_walkers} walkers, "
          f"restart_p={proto.restart_p}")

    # Phase 1: crawl to 90% coverage (device-side early-exit loop).
    state, out = engine.run_until_coverage(
        g, proto, jax.random.key(0), coverage_target=0.9, max_rounds=4096,
    )
    print(f"phase 1: {int(out['rounds'])} rounds to "
          f"{float(out['coverage'])*100:.1f}% of the overlay mapped "
          f"({int(out['messages'])} walk messages)")

    # Phase 2: churn mid-crawl — a block of peers leaves, a runtime
    # bridge appears; the cohort keeps walking the same compiled step.
    g = failures.fail_nodes(g, list(range(5_000, 6_000)))
    g = topology.connect(g, [17], [15_000])
    state = type(state)(
        pos=state.pos, start=state.start,
        visited=state.visited & g.node_mask,  # departed peers un-mapped
    )
    state, out = engine.run_until_coverage_from(
        g, proto, state, jax.random.key(1), coverage_target=0.99,
        max_rounds=8192,
    )
    visited = np.asarray(state.visited)
    alive = np.asarray(g.node_mask)
    print(f"phase 2 (1K peers left, 1 runtime bridge): "
          f"{int(out['rounds'])} more rounds to "
          f"{float(out['coverage'])*100:.1f}% of the live overlay; "
          f"no dead peer mapped: {not (visited & ~alive).any()}")


if __name__ == "__main__":
    main()
