"""GSPMD auto-sharding demo: any engine protocol on a device mesh with
zero protocol changes.

The explicit ring path (examples/mesh_simnode_demo.py) hand-places its
collectives; this is the complementary JAX idiom: put the graph's arrays
on the mesh with named shardings (`parallel/auto.py`), run the UNCHANGED
single-device engine, and let the compiler partition the program and
insert the collectives. With ``method="hybrid-blocked"`` the gather-free
hybrid layout (circular-diagonal shifts + one-hot einsum remainder)
rides along — every op in it is partitionable.

Run: ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/auto_sharding_demo.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env

apply_platform_env()

import jax

from p2pnetwork_tpu.models import SIR, Flood
from p2pnetwork_tpu.parallel import auto
from p2pnetwork_tpu.parallel import mesh as M
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G


def main():
    n = 65_536
    print(f"building {n}-node Watts-Strogatz graph (hybrid layout) ...")
    g = G.watts_strogatz(n, 8, 0.1, seed=0, hybrid=True)

    mesh = M.ring_mesh()
    print(f"mesh: {mesh.devices.size} devices, axis {mesh.axis_names}")
    gs = auto.shard_graph_auto(g, mesh)

    key = jax.random.key(0)
    protocol = Flood(source=0, method="hybrid-blocked")
    _, out = engine.run_until_coverage(gs, protocol, key,
                                       coverage_target=0.99)
    t0 = time.perf_counter()
    _, out = engine.run_until_coverage(gs, protocol, key,
                                       coverage_target=0.99)
    dt = time.perf_counter() - t0
    print(f"flood to 99%: {int(out['rounds'])} rounds, "
          f"{int(out['messages'])} messages, {dt*1000:.1f} ms "
          f"(compiler-placed collectives)")

    # Cross-check: the sharded run is the same program, same results.
    _, ref = engine.run_until_coverage(g, Flood(source=0, method="segment"),
                                       key, coverage_target=0.99)
    assert out["rounds"] == ref["rounds"], (out, ref)
    assert out["messages"] == ref["messages"], (out, ref)
    print("matches the single-device engine exactly")

    # Any protocol scales the same way — here an epidemic, unchanged.
    st, stats = auto.run_auto(gs, SIR(beta=0.3, gamma=0.1,
                                      method="hybrid-blocked"), key, 10)
    import numpy as np

    frac = float(np.asarray(stats["coverage"])[-1])
    print(f"SIR on the same mesh: ever-infected {frac:.1%} after 10 rounds")


if __name__ == "__main__":
    main()
