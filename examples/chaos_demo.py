"""Seeded chaos on a live 4-node overlay, end to end in ~15 seconds.

Four real TCP nodes form a self-healing ring (``reconnect=True`` links with
exponential backoff), all attached to one seeded ``ChaosPlane``. The script
then walks the fault menu:

1. frame drops (seeded, deterministic schedule) under live traffic,
2. added latency,
3. a partition into {A, B} | {C, D} — a rumor flooded on one side must NOT
   cross,
4. heal — the reconnect machinery re-bridges the ring and the rumor
   reconverges on all four nodes,

and closes with the telemetry story: every injected fault and every
recovery step is visible in ONE registry snapshot
(``chaos_injected_failures_total``, ``chaos_active_faults``,
``p2p_reconnect_attempts_total``, ``p2p_reconnect_next_retry_seconds``).

Run: ``python examples/chaos_demo.py`` (no jax required). This is the demo
``make chaos-check`` runs.
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node, NodeConfig, telemetry
from p2pnetwork_tpu.chaos import ChaosPlane

HOST = "127.0.0.1"
SEED = 42


class RumorNode(Node):
    """Flood-with-dedup gossip: rumors spread on message receipt and full
    state is exchanged whenever a connection (re-)establishes."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.rumors = set()

    def add_rumor(self, rumor):
        self.rumors.add(rumor)
        self.send_to_nodes({"rumors": sorted(self.rumors)})

    def node_message(self, conn, data):
        if isinstance(data, dict) and "rumors" in data:
            new = set(data["rumors"]) - self.rumors
            if new:
                self.rumors |= new
                self.send_to_nodes({"rumors": sorted(self.rumors)})
            return
        super().node_message(conn, data)

    def outbound_node_connected(self, conn):
        super().outbound_node_connected(conn)
        if self.rumors:
            self.send_to_node(conn, {"rumors": sorted(self.rumors)})

    def inbound_node_connected(self, conn):
        super().inbound_node_connected(conn)
        if self.rumors:
            self.send_to_node(conn, {"rumors": sorted(self.rumors)})


def wait_for(predicate, timeout=15.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def main():
    reg = telemetry.default_registry()
    plane = ChaosPlane(seed=SEED)
    cfg = dict(reconnect_interval=0.05, reconnect_backoff_base=0.1,
               reconnect_backoff_max=0.5)
    names = ["A", "B", "C", "D"]
    nodes = [RumorNode(HOST, 0, id=n, config=NodeConfig(**cfg)) for n in names]
    plane.attach(*nodes)
    for n in nodes:
        n.start()
    for i, n in enumerate(nodes):
        assert n.connect_with_node(HOST, nodes[(i + 1) % 4].port, reconnect=True)
    wait_for(lambda: all(len(n.all_nodes) >= 2 for n in nodes), what="ring up")
    print(f"ring up: {' -> '.join(names)} -> A   (seed {SEED})")

    # 1. Seeded frame drops under live traffic.
    plane.drop_frames(0.3)
    for i in range(40):
        nodes[0].send_to_node(nodes[0].nodes_outbound[0], {"seq": i})
    wait_for(lambda: reg.value("chaos_injected_failures_total", kind="drop") > 0,
             what="a dropped frame")
    time.sleep(0.3)
    dropped = int(reg.value("chaos_injected_failures_total", kind="drop"))
    print(f"frame drops: {dropped}/40 frames eaten "
          f"(re-run: the same {dropped} — the schedule is seeded)")
    plane.drop_frames(0.0)

    # 2. Added latency.
    plane.add_latency(0.15)
    t0 = time.monotonic()
    before = nodes[1].message_count_recv
    nodes[0].send_to_node(nodes[0].nodes_outbound[0], "slow boat")
    wait_for(lambda: nodes[1].message_count_recv > before, what="delayed frame")
    print(f"added latency: one frame took {time.monotonic() - t0:.2f}s "
          f"(injected 0.15s)")
    plane.add_latency(0.0)

    # 3. Partition {A,B} | {C,D}: a rumor cannot cross.
    plane.partition([["A", "B"], ["C", "D"]])
    nodes[0].add_rumor("split-brain")
    wait_for(lambda: "split-brain" in nodes[1].rumors, what="rumor in group 0")
    time.sleep(0.5)
    assert "split-brain" not in nodes[2].rumors
    assert "split-brain" not in nodes[3].rumors
    print("partition: rumor reached A,B — C,D blind, as injected")

    # 4. Heal: reconnect backoff re-bridges, gossip reconverges.
    plane.heal_partition()
    wait_for(lambda: all("split-brain" in n.rumors for n in nodes),
             what="overlay reconvergence")
    print("heal: overlay re-bridged itself, rumor on all 4 nodes")

    snap = reg.snapshot()
    injected = {s["labels"]["kind"]: int(s["value"])
                for s in snap["chaos_injected_failures_total"]["samples"]}
    reconnects = int(sum(s["value"] for s in
                         snap["p2p_reconnect_attempts_total"]["samples"]))
    print(f"telemetry: injected={injected}, reconnect attempts={reconnects}")
    for family in ("chaos_injected_failures_total", "chaos_active_faults",
                   "p2p_reconnect_attempts_total",
                   "p2p_reconnect_next_retry_seconds"):
        assert family in snap, family

    for n in nodes:
        n.stop()
    for n in nodes:
        n.join(timeout=10)
    print("chaos demo OK")


if __name__ == "__main__":
    main()
