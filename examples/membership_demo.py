"""Sim backend demo: the membership layer — detect the dead, replicate
to the living.

What a real P2P deployment runs continuously on top of a library like
the reference (which only fires ``node_disconnected`` when TCP notices
[ref: p2pnetwork/nodeconnection.py:196-236]): an ACTIVE failure
detector (SWIM-style random ping/ack with suspicion thresholds) and an
anti-entropy replication loop that keeps every living peer's data set
complete despite the losses. Both run here as batched protocols over
one 10K-node overlay with 2% of peers crashed and a lossy network.

Run: ``python examples/membership_demo.py`` (CPU ok; TPU if available).
"""

import sys

sys.path.insert(0, ".")

import jax
import numpy as np

from p2pnetwork_tpu.models import AntiEntropy, FailureDetector
from p2pnetwork_tpu.sim import engine, failures
from p2pnetwork_tpu.sim import graph as G


def main():
    n, dead_frac = 10_000, 0.02
    print(f"building {n}-node Watts-Strogatz overlay ...")
    g = G.watts_strogatz(n, 8, 0.1, seed=0)
    rng = np.random.default_rng(0)
    dead = rng.choice(n, size=int(n * dead_frac), replace=False)

    # --- failure detection: peers crashed, tables still configured.
    gm = failures.mark_unresponsive(g, dead)
    proto = FailureDetector(threshold=3, loss_prob=0.05)
    st, out = engine.run_until_converged(
        gm, proto, jax.random.key(1), stat="undetected", threshold=1,
        max_rounds=4096,
    )
    declared = np.asarray(st.declared)
    truly = np.asarray(proto._dead_watched(gm))
    fp = int((declared & ~truly).sum())
    print(f"FailureDetector: all {int(truly.sum())} dead table slots "
          f"declared in {int(out['rounds'])} rounds "
          f"(5% message loss, threshold 3, {fp} false-positive slots, "
          f"{int(out['messages'])} ping/ack messages)")

    # --- replication among the survivors: edges of the dead are gone now.
    gf = failures.fail_nodes(g, dead)
    proto = AntiEntropy(n_items=64)
    st, out = engine.run_until_converged(
        gf, proto, jax.random.key(2), stat="missing", threshold=1,
        max_rounds=4096,
    )
    have = np.asarray(st.have)
    alive = np.asarray(gf.node_mask)
    print(f"AntiEntropy: 64 items fully replicated to all "
          f"{int(alive.sum())} survivors in {int(out['rounds'])} rounds "
          f"({int(out['messages'])} set exchanges); "
          f"dead peers hold {int(have[~alive].sum())} items")


if __name__ == "__main__":
    main()
