"""Sim backend demo: 100K-node flood as batched graph propagation.

What the reference would need 100 000 threads and hours of 10 ms polls for
[ref: p2pnetwork/nodeconnection.py:220] runs as one compiled scan.
Run: ``python examples/flood_demo.py`` (CPU ok; TPU if available —
set JAX_PLATFORMS=cpu to force CPU).
"""

import sys
import time

sys.path.insert(0, ".")

import jax

from p2pnetwork_tpu.models import Flood
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G


def main():
    n = 100_000
    print(f"building {n}-node Watts-Strogatz graph ...")
    g = G.watts_strogatz(n, 10, 0.1, seed=0, source_csr=True)
    print(f"  {g.n_edges} directed edges")

    protocol = Flood(source=0)
    t0 = time.perf_counter()
    state, out = engine.run_until_coverage(
        g, protocol, jax.random.key(0), coverage_target=0.99, max_rounds=64
    )
    jax.block_until_ready(state.seen)
    first = time.perf_counter() - t0  # includes compile

    t0 = time.perf_counter()
    state, out = engine.run_until_coverage(
        g, protocol, jax.random.key(0), coverage_target=0.99, max_rounds=64
    )
    jax.block_until_ready(state.seen)
    steady = time.perf_counter() - t0

    print(f"flood to 99% coverage: {int(out['rounds'])} rounds, "
          f"{int(out['messages'])} messages")
    print(f"  first run (with compile): {first*1000:.1f} ms")
    print(f"  steady state:             {steady*1000:.1f} ms "
          f"({int(out['messages'])/steady/1e6:.1f}M msgs/sec)")

    # The frontier-adaptive variant: bit-identical results, small rounds
    # as O(k x degree) index-list traversal (models/adaptive_flood.py).
    from p2pnetwork_tpu.models import AdaptiveFlood

    adaptive = AdaptiveFlood(source=0, k=1024)
    state_a, out_a = engine.run_until_coverage(
        g, adaptive, jax.random.key(0), coverage_target=0.99, max_rounds=64
    )
    jax.block_until_ready(state_a.seen)
    t0 = time.perf_counter()
    state_a, out_a = engine.run_until_coverage(
        g, adaptive, jax.random.key(0), coverage_target=0.99, max_rounds=64
    )
    jax.block_until_ready(state_a.seen)
    adaptive_s = time.perf_counter() - t0
    assert out_a == out, "adaptive flood must match the dense run exactly"
    print(f"  adaptive (k=1024):        {adaptive_s*1000:.1f} ms "
          f"— identical rounds/messages/coverage")


if __name__ == "__main__":
    main()
