"""Three-node broadcast demo (sockets backend).

The capability shown in the reference's examples/my_own_p2p_application.py:
three nodes on localhost, a small topology, broadcasts observed via
subclass hooks. Run: ``python examples/my_p2p_application.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node


class MyNode(Node):
    """Subclass-style extension: override the event hooks you care about."""

    def inbound_node_connected(self, node):
        print(f"  [{self.id}] peer connected: {node.id}")
        super().inbound_node_connected(node)

    def node_message(self, node, data):
        print(f"  [{self.id}] message from {node.id}: {data!r}")
        super().node_message(node, data)

    def inbound_node_disconnected(self, node):
        print(f"  [{self.id}] peer left: {node.id}")
        super().inbound_node_disconnected(node)


def main():
    node1 = MyNode("127.0.0.1", 0, id="node-1")
    node2 = MyNode("127.0.0.1", 0, id="node-2")
    node3 = MyNode("127.0.0.1", 0, id="node-3")
    nodes = [node1, node2, node3]
    for n in nodes:
        n.start()

    # Triangle topology.
    node1.connect_with_node("127.0.0.1", node2.port)
    node2.connect_with_node("127.0.0.1", node3.port)
    node3.connect_with_node("127.0.0.1", node1.port)
    time.sleep(0.3)

    print("broadcast from node-1:")
    node1.send_to_nodes("ping from node-1")
    time.sleep(0.3)

    print("dict broadcast from node-2 (zlib-compressed):")
    node2.send_to_nodes({"kind": "status", "height": 42}, compression="zlib")
    time.sleep(0.3)

    for n in nodes:
        print(f"  [{n.id}] sent={n.message_count_send} recv={n.message_count_recv}")
    for n in nodes:
        n.stop()
    for n in nodes:
        n.join()


if __name__ == "__main__":
    main()
