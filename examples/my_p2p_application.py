"""Three-node broadcast demo (sockets backend).

The capability shown in the reference's examples/my_own_p2p_application.py:
three nodes on localhost, a small topology, broadcasts observed via
subclass hooks. The node class lives in its own importable module
(examples/my_peer2peer_node.py), mirroring the reference's documented app
structure [ref: examples/MyOwnPeer2PeerNode.py].
Run: ``python examples/my_p2p_application.py``
"""

import sys
import time

sys.path.insert(0, ".")

from examples.my_peer2peer_node import MyPeer2PeerNode as MyNode


def main():
    node1 = MyNode("127.0.0.1", 0, id="node-1")
    node2 = MyNode("127.0.0.1", 0, id="node-2")
    node3 = MyNode("127.0.0.1", 0, id="node-3")
    nodes = [node1, node2, node3]
    for n in nodes:
        n.start()

    # Triangle topology.
    node1.connect_with_node("127.0.0.1", node2.port)
    node2.connect_with_node("127.0.0.1", node3.port)
    node3.connect_with_node("127.0.0.1", node1.port)
    time.sleep(0.3)

    print("broadcast from node-1:")
    node1.send_to_nodes("ping from node-1")
    time.sleep(0.3)

    print("dict broadcast from node-2 (zlib-compressed):")
    node2.send_to_nodes({"kind": "status", "height": 42}, compression="zlib")
    time.sleep(0.3)

    for n in nodes:
        print(f"  [{n.id}] sent={n.message_count_send} recv={n.message_count_recv}")
    for n in nodes:
        n.stop()
    for n in nodes:
        n.join()


if __name__ == "__main__":
    main()
