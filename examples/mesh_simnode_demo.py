"""Multi-chip JaxSimNode demo: the Node API driving a mesh-partitioned
population.

The same callback-observed SIR epidemic as examples/simnode_demo.py, but
the population lives sharded across a device ring
(parallel/sharded.py) — stepping, run-to-coverage, churn, runtime links,
and a topology-carrying checkpoint all through the standard Node surface.
Run: ``python examples/mesh_simnode_demo.py`` (on a single-device machine
it provisions a virtual 8-device CPU mesh).
"""

import os
import sys

sys.path.insert(0, ".")

# Provision a virtual multi-device CPU platform BEFORE jax initializes, so
# the demo shows real sharding even on a one-chip/CPU machine.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from p2pnetwork_tpu.models import SIR  # noqa: E402
from p2pnetwork_tpu.parallel import mesh as M  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402
from p2pnetwork_tpu.sim.simnode import JaxSimNode  # noqa: E402


def observer(event, main_node, connected_node, data):
    if event == "node_message" and isinstance(data, dict):
        if "sim_round" in data:
            print(f"  round {data['sim_round']:2d}: "
                  f"S={data['s_frac']:.3f} I={data['i_frac']:.3f} "
                  f"R={data['r_frac']:.3f}")
        elif "sim_topology" in data:
            print(f"  topology {data['sim_topology']}: "
                  f"{data['alive_nodes']} peers alive")


def main():
    mesh = M.ring_mesh()  # all local devices
    g = G.watts_strogatz(20_480, 8, 0.05, seed=0)
    proto = SIR(beta=0.3, gamma=0.1, source=0)
    node = JaxSimNode(graph=g, protocol=proto, seed=1, mesh=mesh,
                      dynamic_edges=16, callback=observer)
    print(f"SIR on {g.n_nodes} nodes across a {mesh.devices.size}-device ring")
    node.run_rounds(8)

    node.inject_sim_churn(0.1)            # 10% of peers crash
    node.connect_sim_nodes([4, 9], [15_000, 18_000])  # runtime links
    node.run_rounds(4)

    out = node.run_until_coverage(0.6, max_rounds=128)
    print(f"ever-infected reached {out['coverage']:.1%} of survivors after "
          f"{node.sim_round} total rounds ({node.sim_message_count} messages)")

    node.save_checkpoint("/tmp/mesh_sir_demo.npz")
    resumed = JaxSimNode(graph=g, protocol=proto, seed=1, mesh=mesh,
                         dynamic_edges=16)
    resumed.load_checkpoint("/tmp/mesh_sir_demo.npz")
    same = (np.asarray(resumed.sim_state) == np.asarray(node.sim_state)).all()
    alive = int(resumed.sim_node_alive.sum())
    print(f"restored onto the mesh: {alive} live peers, "
          f"state bit-identical: {bool(same)}")


if __name__ == "__main__":
    main()
