"""Dict-payload demo (sockets backend).

The capability shown in the reference's
examples/my_own_p2p_application_using_dict.py:29 — structured (JSON)
payloads broadcast around a three-node ring and delivered as dicts, the
type round-trip handled by the wire layer [ref: p2pnetwork/
nodeconnection.py:128-143, :173-184].
Run: ``python examples/dict_application.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node


def on_event(event, main_node, connected_node, data):
    if event == "node_message":
        assert isinstance(data, dict), f"expected dict, got {type(data)}"
        print(f"  [{main_node.id}] dict from {connected_node.id}: {data}")


def main():
    node1 = Node("127.0.0.1", 0, id="node-1", callback=on_event)
    node2 = Node("127.0.0.1", 0, id="node-2", callback=on_event)
    node3 = Node("127.0.0.1", 0, id="node-3", callback=on_event)
    nodes = [node1, node2, node3]
    for n in nodes:
        n.start()

    # Ring topology, as in the reference script.
    node1.connect_with_node("127.0.0.1", node2.port)
    node2.connect_with_node("127.0.0.1", node3.port)
    node3.connect_with_node("127.0.0.1", node1.port)
    time.sleep(0.2)

    print("dict broadcast from node-1:")
    node1.send_to_nodes({"name": "demo", "number": 11})
    time.sleep(0.3)

    print("nested dict unicast node-2 -> node-3:")
    peer = node2.nodes_outbound[0]
    node2.send_to_node(peer, {"kind": "block", "header": {"height": 7, "txs": [1, 2, 3]}})
    time.sleep(0.3)

    for n in nodes:
        print(f"  [{n.id}] sent={n.message_count_send} recv={n.message_count_recv}")
    for n in nodes:
        n.stop()
    for n in nodes:
        n.join()


if __name__ == "__main__":
    main()
