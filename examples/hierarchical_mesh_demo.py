"""The hierarchical (multi-slice) scaling story: ICI within a slice, DCN
across slices — demonstrated on an emulated 2-host x 4-chip mesh.

SURVEY section 5 designates this layout as the 10M+ path. Two idioms,
both runnable on the suite's virtual 8-device CPU platform (run with
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``):

1. **ICI-major sharded ring** (parallel/sharded.py over
   ``hierarchical_ring_mesh``): shards hop rank -> rank+1, so the two
   host-boundary hops are the ONLY DCN traffic — per_host-1 of every
   per_host hops ride ICI. The demo lowers the real flood program and
   counts the hops per class from the compiled HLO.

2. **GSPMD auto on the 2-D (dcn, ici) mesh** (parallel/auto.py +
   ``multihost.mesh_2d``): node/edge axes shard over ``ici``. The CPU
   emulation gives XLA no DCN cost model, so it spreads partial work
   across the whole pool — the guarantee that keeps the auto path
   DCN-sane at scale is payload SIZE, not placement: every collective
   is node-extent, and the module's total cross-DCN bytes fit in one
   node-extent array (O(N), never the O(E) of an edge re-shard). The
   demo classifies every collective by axis and prints the byte split.

The placement facts printed here are pinned as assertions in
tests/test_mesh2d_comm.py. The reference has no distributed runtime at
all — its scaling unit is one Python thread per socket
[ref: p2pnetwork/node.py:77-79] — so this layer has no counterpart to
cite beyond the transport it replaces.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402

N_HOSTS, PER_HOST = 2, 4


def ring_story():
    # The counting loops live in the library (parallel/commviz.py) — the
    # same code tests/test_mesh2d_comm.py PINS as assertions, so demo and
    # test cannot drift apart.
    from p2pnetwork_tpu.parallel import commviz

    ici, dcn, _ = commviz.ring_hop_classes(
        commviz.lower_ring_flood_hlo(), lambda d: d // PER_HOST)
    print(f"ring: {ici} ICI hops, {dcn} DCN hops across the compiled "
          f"program ({dcn / max(ici + dcn, 1):.0%} of hops cross slices)")


def mesh2d_story():
    from p2pnetwork_tpu.models import Flood
    from p2pnetwork_tpu.parallel import auto, multihost
    from p2pnetwork_tpu.sim import engine
    from p2pnetwork_tpu.parallel import commviz
    from p2pnetwork_tpu.sim import graph as G

    g = G.watts_strogatz(4096, 6, 0.2, seed=0)
    mesh = multihost.mesh_2d(hosts=N_HOSTS)
    gs = auto.shard_graph_auto(g, mesh, axis_name="ici")
    st, _ = auto.run_auto(gs, Flood(source=0, method="segment"),
                          jax.random.key(0), 6)
    ref, _ = engine.run(g, Flood(source=0, method="segment"),
                        jax.random.key(0), 6)
    assert (np.asarray(st.seen) == np.asarray(ref.seen)).all()

    hlo = engine.run.lower(gs, Flood(source=0, method="segment"),
                           jax.random.key(0), 6).compile().as_text()
    ici_b, dcn_b = commviz.classify_collective_bytes(
        hlo, lambda d: d // PER_HOST)
    print(f"mesh_2d auto: {ici_b} bytes of collectives inside ICI rows, "
          f"{dcn_b} bytes crossing DCN "
          f"(DCN carries {dcn_b / max(ici_b + dcn_b, 1):.0%}) — "
          f"results bit-equal to the single-device engine")


if __name__ == "__main__":
    print(f"emulated layout: {N_HOSTS} hosts x {PER_HOST} chips "
          f"over {len(jax.devices())} virtual devices")
    ring_story()
    mesh2d_story()
