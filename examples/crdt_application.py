"""Sockets backend demo: conflict-free replicated state, no coordinator.

Four peers keep a shared page-view counter, a feature-flag register,
and a presence roster — every peer writes LOCALLY whenever it likes,
states gossip, and the CRDT merge algebra guarantees convergence with
no ordering, no dedup, no acks (contrast examples/coordination_stack.py,
where causal delivery buys ordering at the price of held-back
messages). The reference leaves all of this to its users
[ref: README.md:20].

Run: ``python examples/crdt_application.py``
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import CRDTNode

HOST = "127.0.0.1"


def main():
    nodes = [CRDTNode(HOST, 0, id=f"web-{i}") for i in range(4)]
    for n in nodes:
        n.start()
    for i in range(4):
        for j in range(i + 1, 4):
            nodes[i].connect_with_node(HOST, nodes[j].port)
    while any(len(n.all_nodes) < 3 for n in nodes):
        time.sleep(0.01)

    # Every peer records traffic and sessions concurrently.
    def serve(n, hits):
        for k in range(hits):
            n.mutate("pageviews", "pncounter",
                     lambda c: c.increment(n.id))
            n.mutate("sessions", "orset",
                     lambda s, k=k: s.add(n.id, f"{n.id}#{k}"))

    threads = [threading.Thread(target=serve, args=(n, 25)) for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # One peer flips a feature flag; another expires a session it saw.
    nodes[1].mutate("flags/dark-mode", "lww",
                    lambda r: r.set("web-1", "on"))
    # Observed-remove means OBSERVED: wait until web-0's session has
    # gossiped into node 3 before removing, or the remove tombstones
    # nothing and the concurrent add wins (by design).
    deadline = time.time() + 10
    while time.time() < deadline \
            and "web-0#0" not in nodes[3].set_("sessions"):
        time.sleep(0.02)
    nodes[3].mutate("sessions", "orset", lambda s: s.remove("web-0#0"))

    deadline = time.time() + 10
    while time.time() < deadline and not all(
            n.counter("pageviews").value == 100
            and len(n.set_("sessions").elements()) == 99
            and n.register("flags/dark-mode").value == "on"
            for n in nodes):
        time.sleep(0.05)

    for n in nodes:
        views = n.counter("pageviews").value
        live = len(n.set_("sessions").elements())
        flag = n.register("flags/dark-mode").value
        print(f"{n.id}: {views} pageviews, {live} live sessions, "
              f"dark-mode={flag}")
        assert (views, live, flag) == (100, 99, "on")

    for n in nodes:
        n.stop()
    for n in nodes:
        n.join(timeout=10.0)
    print("4 replicas, 100 concurrent writes, zero coordination — "
          "identical state everywhere.")


if __name__ == "__main__":
    main()
