"""Crash-tolerant supervised runs, end to end in ~20 seconds.

A 20k-node Watts–Strogatz SIR epidemic (PRNG-dependent — the hard case
for resume correctness) runs three ways:

1. an UNINTERRUPTED ``SupervisedRun``: chunked dispatch, a watchdog
   heartbeating every chunk, auto-checkpoints every 4 rounds into an
   atomic, retention-bounded checkpoint directory;
2. the same run KILLED twice mid-flight by the deterministic ``preempt``
   fault (``sim.failures.preempt`` — the SIGKILL stand-in), then revived:
   each revival resumes from the newest durable checkpoint and the final
   state comes out **bit-identical** to the uninterrupted run;
3. a resume across DAMAGE: the newest checkpoint entry is truncated on
   disk, and resume skips it to the previous one — still bit-identical.

Closes with the telemetry story: chunks, checkpoints, resumes, skipped
corrupt entries, watchdog stalls and injected preemptions all in one
registry snapshot.

Run: ``python examples/supervised_run_demo.py`` (CPU is fine). This is
the demo ``make supervise-check`` runs.
"""

import hashlib
import os
import sys
import tempfile

sys.path.insert(0, ".")

import jax
import numpy as np

from p2pnetwork_tpu import telemetry
from p2pnetwork_tpu.models import SIR
from p2pnetwork_tpu.sim import failures
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.supervise import Preempted, SupervisedRun


def digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()[:16]


def supervised(directory) -> SupervisedRun:
    return SupervisedRun(
        G.watts_strogatz(20_000, 8, 0.1, seed=11),
        SIR(beta=0.35, gamma=0.1),
        directory,
        chunk_rounds=4,            # one dispatch + heartbeat per 4 rounds
        checkpoint_every_rounds=4,  # durable progress every chunk
        retain=3,                  # keep the last 3 entries
        deadline_s=60.0,           # wedged-dispatch witness
        on_stall="warn",
    )


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="supervised_demo_")
    rounds = 24

    print("=== 1. uninterrupted supervised run ===")
    run = supervised(os.path.join(workdir, "ref"))
    state_ref, summary = run.run_rounds(jax.random.key(0), rounds)
    print(f"rounds={summary['rounds']} chunks={summary['chunks']} "
          f"checkpoints={summary['checkpoints']} state={digest(state_ref)}")

    print("\n=== 2. preempted twice, revived twice ===")
    run = supervised(os.path.join(workdir, "killed"))
    for kill_at in (8, 16):
        failures.preempt(run, at_round=kill_at)  # deterministic SIGKILL
        try:
            run.run_rounds(jax.random.key(0), rounds)
        except Preempted as e:
            print(f"preempted at round {e.round_index} "
                  f"(durable trail ends at {run.store.latest_round()})")
    state, summary = run.run_rounds(jax.random.key(0), rounds)
    print(f"revived: resumed_from={summary['resumed_from']} "
          f"rounds={summary['rounds']} state={digest(state)}")
    assert digest(state) == digest(state_ref), "resume must be bit-exact"
    print("bit-identical to the uninterrupted run: True")

    print("\n=== 3. resume skips a corrupt checkpoint entry ===")
    run = supervised(os.path.join(workdir, "damaged"))
    failures.preempt(run, at_round=16)
    try:
        run.run_rounds(jax.random.key(0), rounds)
    except Preempted:
        pass
    newest = run.store.entries()[-1]
    path = os.path.join(run.store.directory, newest["file"])
    with open(path, "r+b") as f:  # a kill mid-write / a bad disk
        f.truncate(os.path.getsize(path) // 2)
    print(f"truncated {newest['file']} (round {newest['round']})")
    state, summary = run.run_rounds(jax.random.key(0), rounds)
    print(f"resumed from round {summary['resumed_from']} instead; "
          f"state={digest(state)}")
    assert digest(state) == digest(state_ref)
    print("still bit-identical: True")

    print("\n=== telemetry snapshot (supervision slice) ===")
    snap = telemetry.default_registry().snapshot()
    for name in sorted(snap):
        if name.startswith("supervise_") or "preempt" in name:
            for child in snap[name]["samples"]:
                print(f"  {name}{child['labels']} = {child['value']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
