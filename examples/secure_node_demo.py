"""SecureNode demo: signed, verified messaging between three peers.

The showcase the reference documents but does not ship
[ref: README.md:224-238, examples/README.md:10-16]: every node holds a
keypair, signs what it sends, verifies what it receives; tampered or forged
messages are rejected before they reach the application.
Run: ``python examples/secure_node_demo.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import Node, SecureNode


class Wallet(SecureNode):
    def secure_message(self, node, payload, signer_id, public_key_hex=""):
        print(f"  [{self.id}] VERIFIED from {signer_id}: {payload}")
        super().secure_message(node, payload, signer_id, public_key_hex)

    def secure_message_invalid(self, node, envelope, reason):
        print(f"  [{self.id}] REJECTED ({reason})")
        super().secure_message_invalid(node, envelope, reason)


def main():
    alice = Wallet("127.0.0.1", 0, id="alice")
    bob = Wallet("127.0.0.1", 0, id="bob")
    carol = Wallet("127.0.0.1", 0, id="carol")
    nodes = [alice, bob, carol]
    for n in nodes:
        n.start()
    alice.connect_with_node("127.0.0.1", bob.port)
    bob.connect_with_node("127.0.0.1", carol.port)
    time.sleep(0.3)

    print("signed broadcast from alice:")
    alice.send_to_nodes_signed({"tx": "alice->bob", "amount": 5})
    time.sleep(0.3)

    print("bob relays alice's envelope to carol (still verifies as alice's):")
    env = alice.make_envelope({"tx": "alice->carol", "amount": 7})
    bob.send_to_nodes(env)
    time.sleep(0.3)

    print("mallory forges an envelope claiming to be alice:")
    mallory = Node("127.0.0.1", 0, id="mallory")
    mallory.start()
    mallory.connect_with_node("127.0.0.1", bob.port)
    time.sleep(0.3)
    forged = alice.make_envelope({"tx": "alice->mallory", "amount": 1_000_000})
    forged["payload"]["amount"] = 2_000_000  # tamper
    mallory.send_to_nodes(forged)
    time.sleep(0.3)

    for n in nodes:
        print(f"  [{n.id}] rejected={n.message_count_rerr}")
    for n in nodes + [mallory]:
        n.stop()
    for n in nodes + [mallory]:
        n.join()


if __name__ == "__main__":
    main()
