"""Sockets backend demo: a consistent global snapshot of a LIVE overlay.

The reference cannot answer "how many tokens exist in the system right
now?" while messages are in flight — reading every node's counter at
slightly different instants counts an in-transit token at neither or
both ends (it has no persistence or coordination machinery at all,
SURVEY.md section 5). :class:`~p2pnetwork_tpu.snapshot.SnapshotNode`
adds Chandy-Lamport marker snapshots on top of the ordinary event API:
any peer calls ``take_snapshot()``, every peer records its state plus
the messages caught in flight on each channel, and the recorded cut is
consistent — here, the token total always adds up exactly, no matter
when the snapshot lands.

Run: ``python examples/snapshot_application.py``
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu import SnapshotNode

HOST, TOTAL = "127.0.0.1", 20


class TokenNode(SnapshotNode):
    """Each peer holds tokens and passes them around; all state mutation
    rides the node's event loop (handlers + ``post``), which is what makes
    the cut atomic."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.tokens = 0

    def capture_state(self):
        return {"tokens": self.tokens}

    def app_message(self, node, data):
        if isinstance(data, dict) and "token" in data:
            self.tokens += data["token"]

    def move_token(self):
        def _do():
            if self.tokens > 0 and self.all_nodes:
                self.tokens -= 1
                self.send_to_node(self.all_nodes[0], {"token": 1})

        self.post(_do)


def main():
    a, b, c = (TokenNode(HOST, 0, id=i) for i in "ABC")
    nodes = [a, b, c]
    for n in nodes:
        n.start()
    a.connect_with_node(HOST, b.port)
    b.connect_with_node(HOST, c.port)
    c.connect_with_node(HOST, a.port)
    while any(len(n.all_nodes) < 2 for n in nodes):
        time.sleep(0.01)
    a.post(lambda: setattr(a, "tokens", TOTAL))

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            for n in nodes:
                n.move_token()

    mover = threading.Thread(target=pump, daemon=True)
    mover.start()

    try:
        for trial in range(3):
            time.sleep(0.05)  # let tokens churn between cuts
            sid = nodes[trial].take_snapshot()
            cut = [n.wait_snapshot(sid, timeout=10.0) for n in nodes]
            held = sum(s["state"]["tokens"] for s in cut)
            flying = sum(m["token"] for s in cut
                         for msgs in s["channels"].values() for m in msgs)
            print(f"snapshot {trial + 1} (initiated by {nodes[trial].id}): "
                  f"{held} held + {flying} in flight = {held + flying} "
                  f"(expected {TOTAL})")
            assert held + flying == TOTAL
    finally:
        stop.set()
        mover.join(timeout=5.0)
        for n in nodes:
            n.stop()
        for n in nodes:
            n.join(timeout=10.0)
    print("every cut conserved the token supply — consistent snapshots.")


if __name__ == "__main__":
    main()
