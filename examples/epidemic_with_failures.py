"""SIR epidemic + gossip consensus + fault injection on one graph.

The protocol breadth the reference leaves to its users [ref: README.md:20],
run at population scale: an epidemic over a 100K-node small-world graph,
interrupted by a 40% node-failure event mid-outbreak, then a gossip
averaging pass over the survivors. Runs on CPU or TPU.

Run: ``JAX_PLATFORMS=cpu python examples/epidemic_with_failures.py``
"""

import sys

sys.path.insert(0, ".")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env

apply_platform_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from p2pnetwork_tpu.models import SIR, Gossip  # noqa: E402
from p2pnetwork_tpu.sim import engine, failures  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402


def main():
    n = 100_000
    print(f"building {n}-node Watts-Strogatz graph ...")
    g = G.watts_strogatz(n, 10, 0.1, seed=0)

    proto = SIR(beta=0.25, gamma=0.08, source=0)
    key = jax.random.key(0)

    print("outbreak: 15 rounds on the healthy graph")
    state, stats = engine.run(g, proto, key, 15)
    i_frac = float(np.asarray(stats["i_frac"])[-1])
    print(f"  infected now: {i_frac:.1%}, "
          f"ever-infected: {float(np.asarray(stats['coverage'])[-1]):.1%}")

    print("disaster: 40% of nodes fail")
    gf = failures.random_node_failures(g, jax.random.key(99), 0.4)

    print("epidemic continues on the damaged graph: 25 more rounds")
    # Fresh key: reusing `key` would replay the first rounds' exact
    # infection/recovery draws in the continuation.
    state, stats = engine.run_from(gf, proto, state, jax.random.fold_in(key, 15), 25)
    print(f"  ever-infected (of survivors): "
          f"{float(np.asarray(stats['coverage'])[-1]):.1%}, "
          f"still infected: {float(np.asarray(stats['i_frac'])[-1]):.1%}")

    print("survivors now agree on a value via push-pull gossip (25 rounds)")
    gossip = Gossip(alpha=0.5)
    gstate, gstats = engine.run(gf, gossip, jax.random.key(1), 25)
    var = np.asarray(gstats["variance"])
    print(f"  value variance: {var[0]:.4f} -> {var[-1]:.2e} (consensus)")


if __name__ == "__main__":
    main()
