"""Sim backend demo: latency-weighted routing tables for a whole overlay.

The reference leaves routing to the user: relay a cost advertisement in
``node_message``, keep the best, re-broadcast [ref: README.md:20,
p2pnetwork/node.py:110-116]. Here the same distance-vector protocol runs
as batched Bellman-Ford (models/routing.py): every round is ONE
``propagate_min_plus`` over the whole population, and the converged state
holds exact least-latency costs plus deterministic next-hop tables.

Also shows the structured-overlay story: the same lookup on a Chord-style
finger-table graph (sim/graph.py ``chord``) finishes in O(log n) rounds —
why DHTs layer fingers on top of a ring.

Run: ``python examples/routing_demo.py`` (CPU ok; TPU if available).
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import numpy as np

from p2pnetwork_tpu.models import DistanceVector
from p2pnetwork_tpu.sim import engine
from p2pnetwork_tpu.sim import graph as G


def converge(g, source=0):
    proto = DistanceVector(source=source)
    t0 = time.perf_counter()
    state, out = engine.run_until_converged(
        g, proto, jax.random.key(0), stat="changed", threshold=1,
        max_rounds=512,
    )
    dt = time.perf_counter() - t0
    return state, out, dt


def main():
    n = 100_000
    print(f"building {n}-node Watts-Strogatz overlay with hashed link latencies ...")
    g = G.watts_strogatz(n, 10, 0.1, seed=0)
    # Deterministic per-link latency in [1, 3) ms from the endpoint ids —
    # stand-in for measured RTTs.
    def latency(s, r):
        h = (s.astype(np.uint32) * np.uint32(2654435761) + r.astype(np.uint32))
        return 1.0 + (h % 2048).astype(np.float32) / 1024.0

    g = g.with_weights(latency)

    state, out, dt = converge(g)
    dist = np.asarray(state.dist)[:n]
    parent = np.asarray(state.parent)[:n]
    reached = np.isfinite(dist)
    print(f"DistanceVector: {int(out['rounds'])} rounds in {dt*1000:.0f} ms "
          f"(incl. compile), {reached.mean():.1%} reachable")
    print(f"  latency from node 0: mean {dist[reached].mean():.2f} ms, "
          f"max {dist[reached].max():.2f} ms")
    far = int(np.argmax(np.where(reached, dist, -np.inf)))
    hops = []
    v = far
    while v != 0 and len(hops) < 64:
        hops.append(v)
        v = int(parent[v])
    print(f"  farthest peer {far}: {dist[far]:.2f} ms, "
          f"{len(hops)} next-hop forwards back to the source")

    # The structured-overlay contrast: unit-cost lookup on a Chord graph.
    m = 1 << 16
    gc = G.chord(m)
    state, out, dt = converge(gc)
    dist = np.asarray(state.dist)[:m]
    print(f"Chord {m}-node finger-table overlay: every peer reachable in "
          f"<= {int(dist.max())} hops ({int(out['rounds'])} rounds, "
          f"log2(n) = {m.bit_length() - 1})")


if __name__ == "__main__":
    main()
