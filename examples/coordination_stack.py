"""Sockets backend demo: the coordination stack on one live overlay.

The round's classic-coordination additions working TOGETHER, the way a
real deployment layers them — one node class mixing causal broadcast
(vector clocks), Dijkstra–Scholten termination detection, and
Chandy–Lamport snapshots over the same event API, plus a consistent-
hash ring deciding key ownership:

1. peers run a tiny replicated KV store: writes are CAUSAL broadcasts
   (updates that react to other updates can never apply reversed);
2. ownership of each key is decided by the shared `HashRing` — no
   coordination, every peer computes the same owner;
3. a diffusing QUERY fans out with termination accounting, so the root
   KNOWS when every peer has answered rather than guessing;
4. a SNAPSHOT cuts the live system mid-traffic and the recorded states
   + in-flight messages reconcile exactly.

Run: ``python examples/coordination_stack.py``
"""

import sys
import time

sys.path.insert(0, ".")

from p2pnetwork_tpu.causal import CausalNode
from p2pnetwork_tpu.snapshot import SnapshotNode
from p2pnetwork_tpu.termination import TerminationNode
from p2pnetwork_tpu.utils import HashRing

HOST = "127.0.0.1"


class StackNode(TerminationNode, SnapshotNode, CausalNode):
    """Causal KV writes + termination-detected queries + snapshots.

    MRO note: each layer intercepts its own dict markers in
    ``node_message`` and passes everything else up, so stacking is just
    multiple inheritance — TerminationNode sees work/ack frames,
    SnapshotNode sees snapshot markers (its default ``app_message``
    continues up the MRO — don't override it away, that is the link
    that lets CausalNode see the stamped envelopes), and CausalNode
    delivers the KV writes in causal order.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.store = {}
        self.query_hits = []

    # Causal layer delivers KV writes in dependency order.
    def causal_message(self, node, data):
        if isinstance(data, dict) and "put" in data:
            k, v = data["put"]
            self.store[k] = v

    # Termination layer runs the fan-out query.
    def work_message(self, node, comp_id, q):
        if q["key"] in self.store:
            self.query_hits.append((q["key"], self.store[q["key"]]))
        if q["ttl"] > 0:
            for peer in self.all_nodes:
                self.send_work(peer, {"key": q["key"], "ttl": q["ttl"] - 1})

    # Snapshot layer records the store.
    def capture_state(self):
        return {"store": dict(self.store)}


def main():
    nodes = [StackNode(HOST, 0, id=f"peer-{i}") for i in range(4)]
    for n in nodes:
        n.start()
    # Fully connected: causal broadcast (like any BSS deployment) reaches
    # every participant directly — there is no relaying layer here.
    for i in range(4):
        for j in range(i + 1, 4):
            nodes[i].connect_with_node(HOST, nodes[j].port)
    while any(len(n.all_nodes) < 3 for n in nodes):
        time.sleep(0.01)

    # 1+2: causally-broadcast writes, ownership by consistent hashing.
    ring = HashRing([n.id for n in nodes], vnodes=64)
    for k, v in [("alpha", 1), ("beta", 2), ("gamma", 3), ("delta", 4)]:
        owner = ring.owner(k)
        print(f"key {k!r} owned by {owner}")
        next(n for n in nodes if n.id == owner).send_causal({"put": (k, v)})
    deadline = time.time() + 10
    while time.time() < deadline and any(len(n.store) < 4 for n in nodes):
        time.sleep(0.02)
    assert all(len(n.store) == 4 for n in nodes), "writes not replicated"
    print("all 4 causal writes replicated to all 4 peers")

    # 3: a termination-detected query fan-out.
    cid = nodes[0].start_diffusing({"key": "gamma", "ttl": 3})
    assert nodes[0].wait_terminated(cid, timeout=15.0)
    holders = sum(1 for n in nodes if n.query_hits)
    hits = sum(len(n.query_hits) for n in nodes)
    print(f"query terminated globally: 'gamma' found on {holders}/4 peers "
          f"({hits} total hits — TTL flooding revisits)")

    # 4: a consistent cut of the live stores.
    sid = nodes[2].take_snapshot()
    cut = [n.wait_snapshot(sid, timeout=10.0) for n in nodes]
    assert all(s is not None for s in cut)
    stores = [s["state"]["store"] for s in cut]
    assert all(st == stores[0] for st in stores)
    print(f"snapshot cut: {len(cut)} consistent store copies recorded")

    for n in nodes:
        n.stop()
    for n in nodes:
        n.join(timeout=10.0)
    print("coordination stack OK: causal writes + hashed ownership + "
          "termination-detected queries + consistent snapshots")


if __name__ == "__main__":
    main()
