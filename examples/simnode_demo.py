"""JaxSimNode demo: the Node API driving a simulated population.

A callback written for the sockets backend observes a 10K-node SIR epidemic
through the same ``node_message`` event it would use for socket peers.
Run: ``python examples/simnode_demo.py``
"""

import sys

sys.path.insert(0, ".")

from p2pnetwork_tpu.models import SIR
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.sim.simnode import JaxSimNode


def observer(event, main_node, connected_node, data):
    if event == "node_message" and isinstance(data, dict) and "sim_round" in data:
        print(f"  round {data['sim_round']:2d}: "
              f"S={data['s_frac']:.3f} I={data['i_frac']:.3f} R={data['r_frac']:.3f} "
              f"({data['messages']} msgs)")


def main():
    import numpy as np

    from p2pnetwork_tpu.sim import topology

    g = topology.with_capacity(
        G.watts_strogatz(10_000, 8, 0.05, seed=0), extra_edges=32
    )
    proto = SIR(beta=0.3, gamma=0.15, source=0)
    node = JaxSimNode("127.0.0.1", 0, graph=g, protocol=proto, callback=observer)
    print(f"simulating SIR on {g.n_nodes} nodes / {g.n_edges} edges")
    node.run_rounds(15)
    print(f"total simulated messages: {node.sim_message_count}")

    # Topology churn is state: fail 5% of peers, add a few runtime links...
    node.inject_sim_churn(0.05)
    node.connect_sim_nodes([1, 2, 3], [5001, 5002, 5003])
    alive = int(np.asarray(node.sim_graph.node_mask).sum())
    node.save_checkpoint("/tmp/sir_demo.npz")
    print(f"checkpoint saved with {alive} live nodes + runtime links")

    # ...and a restored node resumes on the damaged/grown network, not the
    # pristine build — no manual damage re-application.
    resumed = JaxSimNode(graph=g, protocol=proto, callback=observer)
    resumed.load_checkpoint("/tmp/sir_demo.npz")
    r_alive = int(np.asarray(resumed.sim_graph.node_mask).sum())
    print(f"restored node sees {r_alive} live nodes "
          f"(topology restored: {r_alive == alive})")
    resumed.run_rounds(5)


if __name__ == "__main__":
    main()
