"""JaxSimNode demo: the Node API driving a simulated population.

A callback written for the sockets backend observes a 10K-node SIR epidemic
through the same ``node_message`` event it would use for socket peers.
Run: ``python examples/simnode_demo.py``
"""

import sys

sys.path.insert(0, ".")

from p2pnetwork_tpu.models import SIR
from p2pnetwork_tpu.sim import graph as G
from p2pnetwork_tpu.sim.simnode import JaxSimNode


def observer(event, main_node, connected_node, data):
    if event == "node_message" and isinstance(data, dict) and "sim_round" in data:
        print(f"  round {data['sim_round']:2d}: "
              f"S={data['s_frac']:.3f} I={data['i_frac']:.3f} R={data['r_frac']:.3f} "
              f"({data['messages']} msgs)")


def main():
    g = G.watts_strogatz(10_000, 8, 0.05, seed=0)
    node = JaxSimNode(
        "127.0.0.1", 0,
        graph=g, protocol=SIR(beta=0.3, gamma=0.15, source=0),
        callback=observer,
    )
    print(f"simulating SIR on {g.n_nodes} nodes / {g.n_edges} edges")
    node.run_rounds(15)
    print(f"total simulated messages: {node.sim_message_count}")
    node.save_checkpoint("/tmp/sir_demo.npz")
    print("checkpoint saved to /tmp/sir_demo.npz (resume with load_checkpoint)")


if __name__ == "__main__":
    main()
