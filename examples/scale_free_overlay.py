"""A scale-free (Barabási–Albert) overlay end to end: the hub problem and
the lowerings that solve it.

Real P2P overlays are degree-skewed — preferential attachment gives a few
supernodes thousands of links (the reference's users meet this the moment
they crawl a real network [ref: README.md:20]). Skew poisons the padded
neighbor-table layout: ONE hub widens EVERY row, measured at 178x padding
waste on 100K BA (BENCH.md "gather floor"). This demo shows the framework
handling it structurally:

- the graph builds with ``skew_table=True``: the two-level virtual-row
  layout (ops/skew.py) keeps padding waste ~1.3x whatever the skew, and
  ``method="auto"`` routes aggregation through it;
- ``AdaptiveFlood`` budgets its sparse rounds by out-edge MASS in
  fixed-width work items, so a waking hub is charged for its whole row
  (chunked) instead of widening every item's gather;
- the protocol sweep — flood, gossip, k-core, walker discovery — runs
  unchanged: lowerings are a graph property, not a protocol rewrite.

Run: ``JAX_PLATFORMS=cpu python examples/scale_free_overlay.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from p2pnetwork_tpu.utils.jax_env import apply_platform_env  # noqa: E402

apply_platform_env()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from p2pnetwork_tpu.models import (AdaptiveFlood, Flood, Gossip,  # noqa: E402
                                   KCore, RandomWalks)
from p2pnetwork_tpu.ops import segment  # noqa: E402
from p2pnetwork_tpu.sim import engine  # noqa: E402
from p2pnetwork_tpu.sim import graph as G  # noqa: E402

N, M = 50_000, 4


def main():
    g = G.barabasi_albert(N, M, seed=0, skew_table=True, source_csr=True)
    deg = np.asarray(g.in_degree)
    print(f"{N} nodes, {g.n_edges} directed edges; max degree "
          f"{int(deg.max())} vs median {int(np.median(deg[:N]))} — "
          f"that one hub would pad the plain table "
          f"{int(deg.max()) / max(np.median(deg[:N]), 1):.0f}x wide")
    t = g.skew
    print(f"two-level table: width {t.width}, {t.n_rows} virtual rows, "
          f"{t.n_slots / g.n_edges:.2f}x padding waste "
          f"(auto routes to: {segment._auto_method(g)!r})")

    key = jax.random.key(0)

    # Flood: the canonical protocol, bit-identical dense/adaptive.
    _, out = engine.run_until_coverage(
        g, AdaptiveFlood(source=0, method="auto", k=1024), key,
        coverage_target=0.99)
    _, ref = engine.run_until_coverage(
        g, Flood(source=0, method="segment"), key, coverage_target=0.99)
    assert out == ref, "adaptive flood diverged from the dense oracle"
    print(f"flood: 99% of the overlay in {int(out['rounds'])} rounds, "
          f"{int(out['messages'])} messages (hubs make it FAST — compare "
          f"a quasi-regular overlay's ~11 rounds at this size)")

    # Gossip averaging: hubs mix aggressively.
    _, gout = engine.run_until_converged(
        g, Gossip(alpha=0.5), key, stat="variance", threshold=1e-6,
        max_rounds=256)
    print(f"gossip: value variance to 1e-6 in {int(gout['rounds'])} rounds")

    # k-core: preferential attachment has degeneracy exactly m — every
    # node entered with m links, so the m-core is the whole overlay and
    # the (m+1)-core peels to nothing. Hubs don't deepen the core.
    cores = {}
    for k in (M, M + 1):
        st, _ = engine.run_until_converged(
            g, KCore(k=k, method="auto"), key, stat="removed",
            threshold=1, max_rounds=256)
        cores[k] = int(np.asarray(st.in_core)[:N].sum())
    print(f"k-core: the {M}-core holds {cores[M]}/{N} nodes "
          f"(everything but the under-attached seed), the {M + 1}-core "
          f"is empty ({cores[M + 1]}) — BA's degeneracy is exactly m, "
          f"hubs notwithstanding")

    # Discovery: a walker cohort maps the overlay; batched super-steps
    # amortize the rounds-bound crawl's per-iteration floor, bit-exactly.
    _, wout = engine.run_until_coverage(
        g, RandomWalks(n_walkers=512), key, coverage_target=0.9,
        max_rounds=4096, steps_per_round=16)
    print(f"discovery: 512 walkers visit 90% of the overlay in "
          f"{int(wout['rounds'])} rounds "
          f"({int(wout['messages'])} hops; hubs are crossroads — "
          f"most walks route through them)")


if __name__ == "__main__":
    main()
